"""Seeded GRAFT005 violation: a declared hot region with no named scope.

tests/test_analysis.py checks it against the contract map
{"gram": ("graft005_missing_scope.py", "hot_gram_panel")}: `hot_gram_panel`
lost its scope annotation (caught); `covered_fn` keeps one (clean).
"""

import jax.numpy as jnp

from svd_jacobi_tpu.obs.scopes import scope


def hot_gram_panel(x):
    return jnp.einsum("kmi,kmj->kij", x, x)   # no scope("gram"): GRAFT005


def covered_fn(x):
    with scope("rotations"):
        return x * 2
