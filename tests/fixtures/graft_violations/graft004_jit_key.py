"""Seeded GRAFT004 violations: jit cache-key hygiene."""

from functools import partial

import jax

_STATICS = ("mode", "missing_name")


@partial(jax.jit, static_argnames=("schedule", "ghost"))
def bad_static_default(x, schedule=[0, 1, 2], *, ghost_typo=None):
    # "schedule" defaults to an UNHASHABLE list (raises at call time);
    # "ghost" names no parameter (silently traced -> retrace per value).
    return x


@partial(jax.jit, static_argnames=_STATICS)
def bad_via_module_const(x, mode="fast"):
    # _STATICS resolves to ("mode", "missing_name"): the second is absent.
    return x


def _impl(y, *, width=4):
    return y * width


good_wrapped = partial(jax.jit, static_argnames=("width",))(_impl)
