"""Seeded failing fixtures for the GRAD001 analysis pass.

A checker that cannot fail its fixture proves nothing (the same
discipline as tests/fixtures/graft_violations): these are the two
violation shapes GRAD001 exists to catch, injected into the pass's
parameter seams by tests/test_grad.py.
"""

import jax.numpy as jnp


def silent_fallback_loss(a):
    """What a silent fallback looks like: the loss differentiates
    `jnp.linalg.svd` at the FULL input shape — its `svd` primitive (and
    AD rule) run the whole problem, and the package's sweep while_loop
    never appears in the trace. Both GRAD001 trace contracts must fire
    on this."""
    return jnp.sum(jnp.linalg.svd(a, full_matrices=False,
                                  compute_uv=False))


def unbudgeted_grad_budgets():
    """A RETRACE_BUDGETS ledger with one grad jit entry dropped — the
    unguarded-compile-surface fixture for GRAD001's budget check."""
    from svd_jacobi_tpu.config import RETRACE_BUDGETS
    budgets = dict(RETRACE_BUDGETS)
    budgets.pop("grad._svd_vjp_jit")
    return budgets
