"""Seeded CONC001 blocking-under-lock violations: fsync, `.result()`,
`block_until_ready`, and a sleep reached through a call, all while
holding a service-tier lock — the worker-wedge class."""

import os
import threading
import time


class Hot:
    def __init__(self):
        self._lock = threading.Lock()     # service tier (test order)

    def fsync_under_lock(self, fd):
        with self._lock:
            os.fsync(fd)                  # CONC001: fsync under hot lock

    def result_under_lock(self, fut):
        with self._lock:
            return fut.result()           # CONC001: .result() under lock

    def device_sync_under_lock(self, x):
        with self._lock:
            x.block_until_ready()         # CONC001: device sync under lock

    def _stall_helper(self):
        time.sleep(0.01)

    def blocking_via_call(self):
        with self._lock:
            self._stall_helper()          # CONC001: sleeps via call
