"""Seeded CONC001 guarded-by violation: `value` is written under the
class lock in one method and bare in another — the bare write races
the locked read-modify-write. `__init__` writes are exempt
(pre-publication), and the pragma'd staging write is suppressed."""

import threading


class Counter:
    def __init__(self):
        self._lock = threading.Lock()     # service tier (test order)
        self.value = 0
        self.epoch = 0

    def locked_bump(self):
        with self._lock:
            self.value += 1

    def racy_reset(self):
        self.value = 0                    # CONC001: bare vs locked_bump

    def locked_epoch(self):
        with self._lock:
            self.epoch += 1

    def staged_epoch(self):
        # graftlock: ok(fixture justification: caller guarantees quiescence)
        self.epoch = 0
