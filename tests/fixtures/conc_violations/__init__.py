"""Seeded graftlock (CONC001-003) violation fixtures.

Never imported by the package — parsed by tests/test_concurrency.py
with per-fixture LOCK_ORDER dicts to prove every rule demonstrably
fires (and that `# graftlock: ok(reason)` pragmas suppress). The one
exception is conc002_deadlock.py, which IS imported and executed under
`sanitizer.capture()` to seed a runtime lock-graph cycle.
"""
