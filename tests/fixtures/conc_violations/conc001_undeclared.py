"""Seeded CONC001 inventory-completeness violations: two locks with no
declared tier (the test lints with an empty LOCK_ORDER), one excused by
a justified pragma."""

import threading

_global_lock = threading.Lock()           # CONC001: undeclared


class Orphan:
    def __init__(self):
        self._mystery = threading.RLock()  # CONC001: undeclared
        # graftlock: ok(fixture justification: scratch lock, never nested)
        self._excused = threading.Lock()
