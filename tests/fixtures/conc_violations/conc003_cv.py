"""Seeded CONC003 condition-variable violations: an unlooped wait, an
unbounded wait, and a notify without the owning lock (which is also a
CONC001 guarded-by hit on `ready` — the bare write races the locked
ones). `ok_wait`/`ok_notify` are the conforming shapes and must stay
clean."""

import threading


class Waiter:
    def __init__(self):
        self._cond = threading.Condition()   # queue tier (test order)
        self.ready = False

    def ok_wait(self):
        with self._cond:
            while not self.ready:
                self._cond.wait(0.1)

    def unlooped_wait(self):
        with self._cond:
            if not self.ready:
                self._cond.wait(0.1)      # CONC003: not predicate-looped

    def unbounded_wait(self):
        with self._cond:
            while not self.ready:
                self._cond.wait()         # CONC003: no timeout

    def notify_outside(self):
        self.ready = True                 # CONC001: bare write to ready
        self._cond.notify_all()           # CONC003: lock not held

    def ok_notify(self):
        with self._cond:
            self.ready = True
            self._cond.notify_all()
