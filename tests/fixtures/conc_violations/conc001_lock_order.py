"""Seeded CONC001 lock-order violations.

The test declares `_outer` at tier router, `_inner` at tier obs, and
`_peer_a`/`_peer_b` both at tier cache. Expected findings: the direct
inversion, the same-rank pair, the inversion reached through a call,
the plain-Lock re-acquisition, and the empty pragma (whose inversion
also still fires). The justified pragma suppresses its inversion.
"""

import threading


class Box:
    def __init__(self):
        self._outer = threading.Lock()    # router tier (test order)
        self._inner = threading.Lock()    # obs tier
        self._peer_a = threading.Lock()   # cache tier
        self._peer_b = threading.Lock()   # cache tier

    def forward(self):
        with self._outer:
            with self._inner:             # fine: router -> obs
                pass

    def inverted(self):
        with self._inner:
            with self._outer:             # CONC001: obs -> router
                pass

    def same_rank(self):
        with self._peer_a:
            with self._peer_b:            # CONC001: no declared order
                pass

    def take_outer(self):
        with self._outer:
            pass

    def inverted_via_call(self):
        with self._inner:
            self.take_outer()             # CONC001: inversion via call

    def self_deadlock(self):
        with self._inner:
            with self._inner:             # CONC001: plain Lock re-taken
                pass

    def inverted_but_justified(self):
        with self._inner:
            # graftlock: ok(fixture justification: outer is quiesced here)
            with self._outer:
                pass

    def empty_pragma(self):
        with self._inner:
            # graftlock: ok()
            with self._outer:             # CONC001 x2: inversion + bare pragma
                pass
