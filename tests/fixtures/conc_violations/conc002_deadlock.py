"""Seeded CONC002 runtime deadlock: two locks taken in opposite orders
by two (sequential) threads. The program never actually wedges — that
is the point of lockdep-style detection: traversing both orders once is
enough for the acquisition graph to close the a->b->a cycle."""

import threading


def build_cycle():
    a = threading.Lock()
    b = threading.Lock()
    hits = []

    def ab():
        with a:
            with b:
                hits.append("ab")

    def ba():
        with b:
            with a:                       # the inverted order
                hits.append("ba")

    for fn in (ab, ba):
        t = threading.Thread(target=fn, name=f"conc002-{fn.__name__}")
        t.start()
        t.join()
    return hits
