"""Worker process for the chaos-lane kill-then-resume test — NOT a test
module.

Runs `utils.checkpoint.svd_checkpointed` with the `resilience.chaos`
SIGTERM hook armed: the checkpoint loop delivers a REAL SIGTERM to this
process at the end of the armed sweep, the production handler writes one
final snapshot, and the process dies a signal death (the parent asserts
returncode == -SIGTERM and that the snapshot holds exactly that sweep).
The matrix is regenerated from the seed, so the parent can resume the
identical solve in its own process.
"""

import sys


def main():
    ckpt, kill_sweep = sys.argv[1], int(sys.argv[2])

    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)

    import jax.numpy as jnp

    from svd_jacobi_tpu import SVDConfig
    from svd_jacobi_tpu.resilience import chaos
    from svd_jacobi_tpu.utils import checkpoint, matgen

    a = matgen.random_dense(48, 48, seed=33, dtype=jnp.float64)
    with chaos.sigterm_at_sweep(kill_sweep):
        # `every` beyond the sweep count: the ONLY snapshot that can exist
        # afterwards is the SIGTERM-triggered final one.
        checkpoint.svd_checkpointed(a, path=ckpt, every=1000,
                                    config=SVDConfig(block_size=4))
    print("worker survived SIGTERM?!", flush=True)  # must be unreachable
    sys.exit(99)


if __name__ == "__main__":
    main()
