"""The telemetry subsystem (svd_jacobi_tpu.obs): jit-safe metrics, run
manifests, robust tracing.

What is actually being proven:

  * the event stream observes the FUSED solve (events emitted from inside
    `lax.while_loop` via `jax.debug.callback`), on both the single-device
    and the mesh path — not a host-stepped replica of it;
  * the mesh path reports each sweep exactly ONCE (the per-device
    replicated deliveries are collapsed by the dispatcher);
  * the zero-telemetry path lowers to HLO with no callbacks, and the HLO
    is independent of the host-side enable flag — telemetry is a static
    trace-time property, so leaving it off cannot perturb production
    solves;
  * manifest records round-trip through JSONL with schema validation.
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import svd_jacobi_tpu as sj
from svd_jacobi_tpu import SVDConfig, obs, solver
from svd_jacobi_tpu.obs import manifest, metrics
from svd_jacobi_tpu.utils import matgen

CFG = SVDConfig(max_sweeps=24)


def _ref_sigma(a):
    return np.linalg.svd(np.asarray(a, np.float64), compute_uv=False)


class TestMetricsSingleDevice:
    def test_capture_fused_pallas_path(self):
        """Per-sweep events from inside the fused kernel-path solve, with
        off-norm trajectory and rotation-round counters."""
        a = matgen.random_dense(96, 96, dtype=jnp.float32, seed=3)
        with metrics.capture() as events:
            r = sj.svd(a, config=CFG)
        sweeps = [e for e in events if e["event"] == "sweep"]
        assert len(sweeps) == int(r.sweeps)
        assert [e["sweep"] for e in sweeps] == list(range(1, len(sweeps) + 1))
        # "fused" when the compiled fused kernels run; "kernel" on the
        # interpret-mode rounds (CPU backend).
        assert sweeps[0]["path"] in ("fused", "kernel")
        # The final event's off-norm is the solve's reported statistic.
        assert sweeps[-1]["off_rel"] == pytest.approx(float(r.off_rel))
        for e in sweeps:
            assert 0 <= e["rounds_rotated"] <= e["rounds_total"]
        # Convergence: the deflation endgame rotates fewer rounds.
        assert sweeps[-1]["rounds_rotated"] <= sweeps[0]["rounds_rotated"]
        # The solve is still correct with telemetry baked in.
        np.testing.assert_allclose(np.asarray(r.s, np.float64),
                                   _ref_sigma(a), rtol=1e-4, atol=1e-4)

    def test_capture_xla_path(self):
        """The XLA block-solver path (f64 -> qr-svd) emits the same
        stream shape."""
        a = matgen.random_dense(48, 48, dtype=jnp.float64, seed=4)
        with metrics.capture() as events:
            r = sj.svd(a, config=CFG)
        sweeps = [e for e in events if e["event"] == "sweep"]
        assert len(sweeps) == int(r.sweeps)
        assert sweeps[0]["path"] == "xla"
        assert all(isinstance(e["off_rel"], float) for e in sweeps)

    def test_disabled_is_silent(self):
        a = matgen.random_dense(48, 48, dtype=jnp.float32, seed=5)
        sink_hits = []
        remove = metrics.add_sink(sink_hits.append)
        try:
            sj.svd(a, config=CFG)
            metrics.flush()
        finally:
            remove()
        assert sink_hits == []

    def test_capture_restores_flag_and_nests(self):
        assert not metrics.enabled()
        with metrics.capture() as outer:
            assert metrics.enabled()
            with metrics.capture() as inner:
                metrics.emit  # noqa: B018  (flag state is what's under test)
                assert metrics.enabled()
            assert metrics.enabled()
        assert not metrics.enabled()
        assert outer == [] and inner == []


class TestMetricsMesh:
    def test_capture_sharded_reports_once(self, eight_devices):
        """The mesh solve emits pmax-replicated values once per local
        device; the dispatcher must collapse them to ONE event per sweep."""
        from svd_jacobi_tpu.parallel import sharded
        a = matgen.random_dense(96, 96, dtype=jnp.float32, seed=6)
        with metrics.capture() as events:
            r = sharded.svd(a, config=CFG)
        sweeps = [e for e in events if e["event"] == "sweep"]
        assert len(sweeps) == int(r.sweeps)          # not 8x
        assert [e["sweep"] for e in sweeps] == list(range(1, len(sweeps) + 1))
        assert sweeps[0]["path"] == "sharded"
        assert sweeps[0]["devices"] == 8
        assert sweeps[-1]["off_rel"] == pytest.approx(float(r.off_rel))

    def test_sharded_result_unchanged_by_telemetry(self, eight_devices):
        from svd_jacobi_tpu.parallel import sharded
        a = matgen.random_dense(96, 96, dtype=jnp.float32, seed=7)
        r_plain = sharded.svd(a, config=CFG)
        with metrics.capture():
            r_tel = sharded.svd(a, config=CFG)
        np.testing.assert_array_equal(np.asarray(r_plain.s),
                                      np.asarray(r_tel.s))


class TestHloEquivalence:
    """Telemetry must be free when off: the flag is static, so the
    telemetry-off program contains no callback and is byte-identical no
    matter what the host-side enable flag says (i.e. identical to the
    pre-telemetry seed program modulo scope names, which are metadata on
    the same ops). The check itself is now a reusable graftcheck pass
    (`analysis.hlo_checks.check_telemetry_invariance`, HLO003) run over
    EVERY entry probe — this class pins the original single-entry form to
    the pass and keeps the structural carry check."""

    def _probe(self):
        from svd_jacobi_tpu.analysis.entries import EntryProbe
        a = jnp.zeros((16, 16), jnp.float32)
        return EntryProbe(
            name="padded_qr", fn=solver._svd_padded, args=(a,),
            kwargs=dict(n=16, compute_u=True, compute_v=True, full_u=False,
                        nblocks=2, tol=1e-7, max_sweeps=4,
                        precision="highest", gram_dtype_name="float32",
                        method="qr-svd", criterion="rel", telemetry=False))

    def test_off_has_no_callback_and_ignores_host_flag(self):
        from svd_jacobi_tpu.analysis import hlo_checks
        probe = self._probe()
        assert hlo_checks.check_telemetry_invariance(probe) == []
        # The raw invariants the pass encodes, asserted once directly.
        text_off = probe.lower().as_text()
        text_on = probe.with_kwargs(telemetry=True).lower().as_text()
        assert "callback" not in text_off
        assert "callback" in text_on and text_on != text_off

    def test_pass_runs_on_every_entry(self):
        from svd_jacobi_tpu.analysis import entries, hlo_checks
        for probe in entries.single_device_probes(include_f64=False):
            assert hlo_checks.check_telemetry_invariance(probe) == [], \
                probe.name

    def test_fused_sweep_off_has_no_extra_carry(self):
        """rounds.sweep with telemetry off returns the seed's 5-tuple (no
        rotation counter riding the scan carry)."""
        from svd_jacobi_tpu.ops import rounds
        k, mrows, b = 2, 16, 4
        top = jnp.ones((k, mrows, b), jnp.float32)
        bot = jnp.ones((k, mrows, b), jnp.float32)
        dmax2 = jnp.float32(1.0)
        out = jax.eval_shape(
            lambda t, bo: rounds.sweep(t, bo, None, None, dmax2, 1e-6,
                                       interpret=True, polish=False,
                                       bf16_gram=False), top, bot)
        assert len(out) == 5
        out_t = jax.eval_shape(
            lambda t, bo: rounds.sweep(t, bo, None, None, dmax2, 1e-6,
                                       interpret=True, polish=False,
                                       bf16_gram=False, telemetry=True),
            top, bot)
        assert len(out_t) == 6


class TestManifest:
    def _record(self, **over):
        kw = dict(m=64, n=64, dtype="float32", config=SVDConfig(),
                  solve={"time_s": 1.0, "sweeps": 8, "off_norm": 1e-7},
                  stages=[{"name": "solve", "time_s": 1.0}],
                  telemetry=[{"event": "sweep", "sweep": 1,
                              "off_rel": 0.5}])
        kw.update(over)
        return manifest.build("cli", **kw)

    def test_round_trip(self, tmp_path):
        path = tmp_path / "m.jsonl"
        rec = self._record(seed=123)
        manifest.append(path, rec)
        manifest.append(path, self._record(telemetry=None))
        loaded = manifest.load(path)
        assert len(loaded) == 2
        for r in loaded:
            manifest.validate(r)
        assert loaded[0] == json.loads(json.dumps(rec))  # JSON-stable
        assert loaded[0]["seed"] == 123                  # extras survive
        assert loaded[1]["telemetry"] is None

    def test_validate_rejects_missing_and_wrong_types(self):
        rec = self._record()
        bad = dict(rec)
        del bad["environment"]
        with pytest.raises(ValueError, match="environment"):
            manifest.validate(bad)
        bad = json.loads(json.dumps(rec))
        bad["solve"]["sweeps"] = "eight"
        with pytest.raises(ValueError, match="solve.sweeps"):
            manifest.validate(bad)
        bad = json.loads(json.dumps(rec))
        bad["schema_version"] = 999
        with pytest.raises(ValueError, match="schema_version"):
            manifest.validate(bad)

    def test_config_hash_is_content_addressed(self):
        h1 = manifest.config_hash(SVDConfig())
        h2 = manifest.config_hash(SVDConfig())
        h3 = manifest.config_hash(SVDConfig(max_sweeps=7))
        assert h1 == h2 != h3

    def test_summarize_and_diff_render(self):
        rec = self._record()
        text = manifest.summarize(rec)
        assert "64x64" in text and "sweep" in text
        d = manifest.diff(rec, self._record(
            solve={"time_s": 2.0, "sweeps": 9, "off_norm": 1e-7}))
        assert "solve.time_s" in d and "+100.0%" in d


class TestTraceRobustness:
    def test_creates_dir_and_degrades_to_warning(self, tmp_path,
                                                 monkeypatch):
        target = tmp_path / "nested" / "trace_out"

        def boom(*a, **k):
            raise RuntimeError("no profiler on this backend")

        monkeypatch.setattr(jax.profiler, "start_trace", boom)
        ran = False
        with pytest.warns(RuntimeWarning, match="profiler unavailable"):
            with obs.trace(target):
                ran = True
        assert ran
        assert target.is_dir()    # created even though tracing failed

    def test_noop_when_mkdir_fails(self, tmp_path, monkeypatch):
        # A file where the dir should go: mkdir raises -> warn, still run.
        clash = tmp_path / "clash"
        clash.write_text("")
        ran = False
        with pytest.warns(RuntimeWarning):
            with obs.trace(clash):
                ran = True
        assert ran


class TestTraceFleetInteraction:
    """obs.trace/XProf + fleet: arming a one-request profiler capture on
    a lane that gets QUARANTINED must warn and skip — never raise inside
    a dispatch the supervisor is already nursing (a probe solve on a
    quarantined lane is the canonical case)."""

    def _svc(self):
        from svd_jacobi_tpu.serve import ServeConfig, SVDService
        return SVDService(ServeConfig(
            buckets=((32, 32, "float64"),),
            solver=sj.SVDConfig(block_size=4),
            lanes=2, steal=False, supervise_interval_s=0.02,
            lane_probe_interval_s=600.0))

    def test_quarantined_lane_capture_warns_and_skips(self, tmp_path):
        import warnings as _warnings

        from svd_jacobi_tpu.serve.queue import Request
        from svd_jacobi_tpu.serve.service import Ticket
        svc = self._svc().start()
        try:
            lane = svc.fleet.lanes[0]
            svc.fleet.evict(lane, "test_forced")
            ticket = Ticket("rq-traced")
            req = Request(
                id="rq-traced", a=np.zeros((32, 32)), m=32, n=32,
                orig_shape=(32, 32), transposed=False,
                bucket=list(svc.buckets)[0], compute_u=False,
                compute_v=False, degraded=False, deadline=None,
                deadline_s=None, submitted=0.0, cancel=ticket._cancel,
                ticket=ticket)
            svc.capture_request_trace("rq-traced", tmp_path / "xprof")
            with pytest.warns(RuntimeWarning, match="quarantined"):
                win = svc._trace_window_for(req, lane)
            assert win is None                    # skipped, not raised
            # The arm is consumed: a later healthy dispatch of the same
            # id does not resurrect a stale capture.
            with _warnings.catch_warnings():
                _warnings.simplefilter("error")
                assert svc._trace_window_for(req, lane) is None
        finally:
            svc.stop(drain=False, timeout=30.0)

    def test_probe_on_quarantined_lane_survives_armed_capture(self):
        """End to end: arm a capture for the recovery PROBE itself (it
        dispatches on the quarantined lane by design); the probe must
        still run, the lane must still recover — the capture is simply
        skipped with a warning, never an exception mid-supervisor-tick."""
        import time as _time
        import warnings as _warnings

        from svd_jacobi_tpu.serve import LaneState, ServeConfig, SVDService
        svc = SVDService(ServeConfig(
            buckets=((32, 32, "float64"),),
            solver=sj.SVDConfig(block_size=4),
            lanes=2, steal=False, supervise_interval_s=0.02,
            lane_probe_interval_s=0.05, lane_probe_timeout_s=120.0)).start()
        try:
            # Probe ids are deterministic: the first probe on lane 0 is
            # "probe-l0-0".
            svc.capture_request_trace("probe-l0-0", "/tmp/xprof-na")
            with _warnings.catch_warnings(record=True) as caught:
                _warnings.simplefilter("always")
                svc.fleet.evict(svc.fleet.lanes[0], "test_forced")
                deadline = _time.monotonic() + 60.0
                while (svc.fleet.lanes[0].state is not LaneState.ACTIVE
                       and _time.monotonic() < deadline):
                    _time.sleep(0.02)
            assert svc.fleet.lanes[0].state is LaneState.ACTIVE
            assert any("quarantined" in str(w.message) for w in caught
                       if issubclass(w.category, RuntimeWarning))
        finally:
            svc.stop(drain=False, timeout=30.0)


class TestPhaseInfo:
    def test_public_accessor_tracks_hybrid_stages(self):
        a = matgen.random_dense(48, 48, dtype=jnp.float64, seed=9)
        st = solver.SweepStepper(
            a, config=SVDConfig(pair_solver="hybrid", max_sweeps=24))
        state = st.init()
        info = st.phase_info(state)
        assert info.stage == "bulk"
        assert info.method == "gram-eigh" and info.criterion == "abs"
        seen = {info.stage}
        while st.should_continue(state):
            state = st.step(state)
            seen.add(st.phase_info(state).stage)
        assert seen == {"bulk", "polish"}
        r = st.finish(state)
        np.testing.assert_allclose(np.asarray(r.s), _ref_sigma(a),
                                   rtol=1e-8, atol=1e-10)

    def test_sharded_stepper_inherits_accessor(self, eight_devices):
        from svd_jacobi_tpu.parallel import sharded
        a = matgen.random_dense(96, 96, dtype=jnp.float32, seed=10)
        st = sharded.SweepStepper(a, config=CFG)
        info = st.phase_info(st.init())
        assert info.stage in ("bulk", "single")
        assert isinstance(info.tol, float)


class TestCacheRecords:
    """The "cache" manifest kind (result cache + promotion store events)
    and the serve record's two-phase fields round-trip through
    build -> validate -> append -> load -> summarize."""

    def test_build_cache_round_trip(self, tmp_path):
        from svd_jacobi_tpu.obs import manifest
        path = tmp_path / "m.jsonl"
        for store, event in (("result", "hit"), ("result", "store"),
                             ("result", "evict"), ("result", "invalidate"),
                             ("promotion", "retain"),
                             ("promotion", "promote"),
                             ("promotion", "release"),
                             ("promotion", "evict"),
                             ("promotion", "rescue")):
            rec = manifest.build_cache(
                store=store, event=event, request_id="r1",
                digest="ab" * 32, nbytes=1024)
            manifest.validate(rec)
            manifest.append(path, rec)
        loaded = manifest.load(path)
        assert len(loaded) == 9
        for rec in loaded:
            manifest.validate(rec)
            line = manifest.summarize(rec)
            assert line.startswith("cache ")
            assert "req=r1" in line and "1024 B" in line

    def test_build_cache_optional_fields(self):
        from svd_jacobi_tpu.obs import manifest
        rec = manifest.build_cache(store="result", event="invalidate",
                                   count=3)
        manifest.validate(rec)
        assert rec["request_id"] is None and rec["digest"] is None
        assert "count=3" in manifest.summarize(rec)

    def test_build_cache_rejects_bad_types(self):
        from svd_jacobi_tpu.obs import manifest
        rec = manifest.build_cache(store="result", event="hit")
        rec["bytes"] = "many"
        with pytest.raises(ValueError, match="bytes"):
            manifest.validate(rec)

    def test_serve_phase_fields_round_trip(self, tmp_path):
        from svd_jacobi_tpu.obs import manifest
        path = tmp_path / "m.jsonl"
        sig = manifest.build_serve(
            request_id="rs", m=32, n=32, dtype="float32", bucket="b32",
            queue_wait_s=0.0, solve_time_s=0.1, status="OK", path="base",
            breaker="closed", brownout="FULL", degraded=False,
            deadline_s=None, phase="sigma")
        pro = manifest.build_serve(
            request_id="rs+p", m=32, n=32, dtype="float32", bucket="b32",
            queue_wait_s=0.0, solve_time_s=0.01, status="OK", path="base",
            breaker="closed", brownout="FULL", degraded=False,
            deadline_s=None, phase="promote", promoted_from="rs")
        for rec in (sig, pro):
            manifest.validate(rec)
            manifest.append(path, rec)
        l_sig, l_pro = manifest.load(path)
        assert l_sig["phase"] == "sigma" and l_sig["promoted_from"] is None
        assert l_pro["promoted_from"] == "rs"
        assert "phase=sigma" in manifest.summarize(l_sig)
        assert "phase=promote<-rs" in manifest.summarize(l_pro)
        # The default phase stays out of the summary line (unchanged
        # rendering for the whole pre-two-phase stream).
        full = manifest.build_serve(
            request_id="rf", m=32, n=32, dtype="float32", bucket="b32",
            queue_wait_s=0.0, solve_time_s=0.1, status="OK", path="base",
            breaker="closed", brownout="FULL", degraded=False,
            deadline_s=None)
        assert "phase=" not in manifest.summarize(full)

    def test_serve_phase_wrong_type_rejected(self):
        from svd_jacobi_tpu.obs import manifest
        rec = manifest.build_serve(
            request_id="rx", m=8, n=8, dtype="float32", bucket="b",
            queue_wait_s=0.0, solve_time_s=None, status="OK", path="base",
            breaker="closed", brownout="FULL", degraded=False,
            deadline_s=None)
        rec["phase"] = 7
        with pytest.raises(ValueError, match="phase"):
            manifest.validate(rec)
