"""Target-regime coverage: the b=128 TPU default block path, and
stall/conditioning sweeps across dtype that pin the solver's measured
convergence constants (VERDICT r2 weak #4: the default TPU block path and
the stall-detection constants were untested)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import svd_jacobi_tpu as sj
from svd_jacobi_tpu.config import SVDConfig
from svd_jacobi_tpu.ops import rounds
from svd_jacobi_tpu import solver

HI = jax.lax.Precision.HIGHEST


def test_default_block_size_thresholds():
    """Measured defaults (PROFILE.md item 18): lane-sized 128 from 2048,
    widened to 256 from 8192 where the fused apply crosses the f32 ridge
    and rounds/sweep halve."""
    assert SVDConfig().pick_block_size(2048) == 128
    assert SVDConfig().pick_block_size(4096) == 128
    assert SVDConfig().pick_block_size(8192) == 256
    assert SVDConfig().pick_block_size(65536) == 256
    b, k = solver._plan(2048, 1, SVDConfig())
    assert b == 128 and 2 * k * b == 2048
    b, k = solver._plan(16384, 1, SVDConfig())
    assert b == 256 and 2 * k * b == 16384


def test_b128_sweep_path():
    """One kernel sweep at the TPU-default b=128 block width (n = 1024
    columns in 8 blocks, small m so CPU-interpret stays fast): couplings
    must contract and the block stacks keep their shapes."""
    rng = np.random.default_rng(0)
    m, b, k = 48, 128, 4
    top = jnp.asarray(rng.standard_normal((k, m, b)), jnp.float32)
    bot = jnp.asarray(rng.standard_normal((k, m, b)), jnp.float32)
    dmax2 = rounds._global_dmax2(top, bot)
    t2, b2, _, _, off = rounds.sweep(
        top, bot, None, None, dmax2, 0.0, interpret=True, polish=True,
        bf16_gram=False)
    assert t2.shape == top.shape and b2.shape == bot.shape
    # rank m << n: most couplings cannot be resolved in one sweep, but the
    # sweep must make progress on the Gram off-diagonal mass
    x0 = jnp.concatenate([jnp.concatenate([top, bot], axis=0)[i] for i in range(2 * k)], axis=1)
    x1 = jnp.concatenate([jnp.concatenate([t2, b2], axis=0)[i] for i in range(2 * k)], axis=1)

    def offmass(x):
        g = jnp.einsum("mi,mj->ij", x, x, precision=HI)
        return float(jnp.linalg.norm(g * (1 - jnp.eye(g.shape[0]))))

    assert offmass(x1) < offmass(x0)
    assert float(off) > 0.0


@pytest.mark.parametrize("dtype,cond,serr_tol", [
    (jnp.float32, 1e-5, 5e-6),
    (jnp.float32, 1e-2, 5e-6),
    (jnp.bfloat16, 1e-2, 3e-2),
])
def test_conditioning_sweep_pallas(dtype, cond, serr_tol):
    """Graded spectra across dtype: the solve must terminate well under the
    sweep cap (stall detection / tol constants) with sigma error at the
    dtype's floor and live U columns orthogonal."""
    rng = np.random.default_rng(1)
    n = 96
    s_true = np.geomspace(1.0, cond, n)
    q1, _ = np.linalg.qr(rng.standard_normal((n, n)))
    q2, _ = np.linalg.qr(rng.standard_normal((n, n)))
    a = jnp.asarray(q1 * s_true @ q2.T, dtype)
    cfg = SVDConfig(max_sweeps=32)
    r = sj.svd(a, config=cfg)
    assert int(r.sweeps) < 28          # terminated, not budget-exhausted
    sn = np.asarray(r.s, np.float64)
    s_ref = np.linalg.svd(np.asarray(a, np.float64), compute_uv=False)
    assert np.max(np.abs(sn - s_ref)) / s_ref[0] < serr_tol
    # live columns (sigma above the dtype floor) of U stay orthogonal
    eps = float(jnp.finfo(dtype).eps)
    live = sn > 10 * eps * sn[0]
    un = np.asarray(r.u, np.float64)[:, live]
    gram = un.T @ un
    assert np.max(np.abs(gram - np.eye(gram.shape[0]))) < 50 * np.sqrt(n) * eps


@pytest.mark.parametrize("shape,cu,cv,full", [
    ((96, 96), True, True, False),
    ((160, 96), True, True, True),
    ((96, 96), True, False, False),
    ((96, 96), False, True, False),
])
def test_precondition_double(shape, cu, cv, full):
    """dgejsv-style double preconditioning (second QR, inverted U/V
    bookkeeping: the rotation product becomes V, the normalized columns
    become U) must match the single-precondition accuracy for every
    compute_u/compute_v/full_matrices combination."""
    rng = np.random.default_rng(8)
    m, n = shape
    s_true = np.geomspace(1.0, 1e-3, n)
    q1, _ = np.linalg.qr(rng.standard_normal((m, m)))
    q2, _ = np.linalg.qr(rng.standard_normal((n, n)))
    a = jnp.asarray(q1[:, :n] * s_true @ q2.T, jnp.float32)
    a64 = np.asarray(a, np.float64)
    r = sj.svd(a, config=SVDConfig(precondition="double",
                                   pair_solver="pallas"),
               compute_u=cu, compute_v=cv, full_matrices=full)
    s_ref = np.linalg.svd(a64, compute_uv=False)
    assert np.max(np.abs(np.asarray(r.s, np.float64) - s_ref)) / s_ref[0] < 5e-6
    assert (r.u is None) == (not cu) and (r.v is None) == (not cv)
    if cu:
        u = np.asarray(r.u, np.float64)
        assert u.shape == ((m, m) if full else (m, n))
        assert np.max(np.abs(u.T @ u - np.eye(u.shape[1]))) < 5e-5
    if cv:
        v = np.asarray(r.v, np.float64)
        assert np.max(np.abs(v.T @ v - np.eye(n))) < 5e-5
    if cu and cv:
        u = np.asarray(r.u, np.float64)[:, :n]
        res = np.linalg.norm(u * np.asarray(r.s, np.float64)
                             @ np.asarray(r.v, np.float64).T - a64)
        assert res / np.linalg.norm(a64) < 5e-6


@pytest.mark.parametrize("method", ["hybrid", "qr-svd"])
def test_conditioning_sweep_xla_paths(method):
    """The XLA block-solver paths (used by the sharded solver) under a
    graded spectrum: the measured stall/tol constants in
    solver._should_continue must terminate them without exhausting the
    budget or losing sigma accuracy."""
    rng = np.random.default_rng(2)
    n = 48
    s_true = np.geomspace(1.0, 1e-5, n)
    q1, _ = np.linalg.qr(rng.standard_normal((n, n)))
    q2, _ = np.linalg.qr(rng.standard_normal((n, n)))
    a = jnp.asarray(q1 * s_true @ q2.T, jnp.float32)
    r = sj.svd(a, config=SVDConfig(pair_solver=method, max_sweeps=32))
    assert int(r.sweeps) < 28
    sn = np.asarray(r.s, np.float64)
    s_ref = np.linalg.svd(np.asarray(a, np.float64), compute_uv=False)
    assert np.max(np.abs(sn - s_ref)) / s_ref[0] < 5e-6


@pytest.mark.parametrize("store,cu,cv", [
    ("f32", True, True), ("f32", False, False),
    ("bf16", True, True), ("bf16", False, False),
    ("bf16g", True, True),
])
def test_mixed_bulk_f32_accuracy_class(store, cu, cv):
    """The mixed-bulk regime (SVDConfig.mixed_bulk) must deliver the SAME
    accuracy class as the pure-f32 path in EVERY storage regime
    (mixed_store): the bulk X is discarded and the state reconstituted as
    L @ NS(G) at HIGHEST, so residual and sigma are set by the f32 polish —
    not by the bf16 bulk arithmetic ("f32"/x3), the bf16-STORED X stacks
    ("bf16"), or the bf16-stored rotation product ("bf16g")."""
    rng = np.random.default_rng(11)
    a = jnp.asarray(rng.standard_normal((192, 192)), jnp.float32)
    r = sj.svd(a, config=SVDConfig(mixed_bulk=True, pair_solver="pallas",
                                   mixed_store=store),
               compute_u=cu, compute_v=cv)
    s_ref = np.linalg.svd(np.asarray(a, np.float64), compute_uv=False)
    assert np.max(np.abs(np.asarray(r.s, np.float64) - s_ref)) / s_ref[0] < 2e-6
    if cu and cv:
        u, v = np.asarray(r.u, np.float64), np.asarray(r.v, np.float64)
        res = np.linalg.norm(u * np.asarray(r.s, np.float64) @ v.T
                             - np.asarray(a, np.float64))
        assert res / np.linalg.norm(np.asarray(a)) < 5e-6
        assert np.max(np.abs(u.T @ u - np.eye(192))) < 1e-4
        assert np.max(np.abs(v.T @ v - np.eye(192))) < 1e-4


def test_donate_input_correctness():
    """SVDConfig.donate_input routes through the donating jit twin: same
    results (the caller's buffer may be invalidated; the CPU backend may
    ignore donation, so only correctness is asserted here — the memory
    effect is the measured 30000^2 sigma-only chip row in BASELINE.md)."""
    rng = np.random.default_rng(16)
    an = rng.standard_normal((128, 96)).astype(np.float32)
    r = sj.svd(jnp.asarray(an), config=SVDConfig(donate_input=True))
    s_ref = np.linalg.svd(an.astype(np.float64), compute_uv=False)
    assert np.max(np.abs(np.asarray(r.s, np.float64) - s_ref)) / s_ref[0] < 2e-6


def test_stepper_donate_input_releases_and_solves():
    """donate_input on the host-stepped API: the input buffer is released
    at init (the 30208^2 sigma-only chip row depends on this headroom —
    PROFILE.md item 19), the solve still converges, and checkpoint digest
    validation is refused loudly."""
    rng = np.random.default_rng(17)
    an = rng.standard_normal((128, 128)).astype(np.float32)
    # Unpreconditioned sigma-only (the 30208^2 recipe).
    st = solver.SweepStepper(jnp.asarray(an), compute_u=False,
                             compute_v=False,
                             config=SVDConfig(precondition="off",
                                              donate_input=True))
    state = st.init()
    assert st.a is None
    with pytest.raises(ValueError, match="released"):
        st.input_digest()
    while st.should_continue(state):
        state = st.step(state)
    r = st.finish(state)
    s_ref = np.linalg.svd(an.astype(np.float64), compute_uv=False)
    assert np.max(np.abs(np.asarray(r.s, np.float64) - s_ref)) / s_ref[0] < 5e-6
    # Preconditioned full-vector variant (q1/work survive the release).
    st2 = solver.SweepStepper(jnp.asarray(an),
                              config=SVDConfig(donate_input=True))
    state = st2.init()
    assert st2.a is None
    while st2.should_continue(state):
        state = st2.step(state)
    r2 = st2.finish(state)
    assert np.max(np.abs(np.asarray(r2.s, np.float64) - s_ref)) / s_ref[0] < 5e-6
    res = np.linalg.norm(np.asarray(r2.u, np.float64)
                         * np.asarray(r2.s, np.float64)
                         @ np.asarray(r2.v, np.float64).T
                         - an.astype(np.float64))
    assert res / np.linalg.norm(an) < 5e-6
    # Unpreconditioned + refine-on is unsatisfiable: loud rejection.
    st3 = solver.SweepStepper(jnp.asarray(an),
                              config=SVDConfig(precondition="off",
                                               donate_input=True,
                                               sigma_refine=True))
    with pytest.raises(ValueError, match="refine"):
        st3.init()


def test_mixed_store_validation():
    rng = np.random.default_rng(15)
    a = jnp.asarray(rng.standard_normal((96, 96)), jnp.float32)
    with pytest.raises(ValueError, match="mixed_store"):
        sj.svd(a, config=SVDConfig(mixed_bulk=True, pair_solver="pallas",
                                   mixed_store="fp8"))


def test_mixed_bulk_matches_pure_f32_on_padding():
    """Mixed reconstitution relies on padded columns never mixing
    ([work | 0] @ G == work @ G[:n]); a non-multiple-of-block n exercises
    real padding."""
    rng = np.random.default_rng(12)
    a = jnp.asarray(rng.standard_normal((150, 100)), jnp.float32)
    r = sj.svd(a, config=SVDConfig(mixed_bulk=True, pair_solver="pallas",
                                   block_size=16))
    s_ref = np.linalg.svd(np.asarray(a, np.float64), compute_uv=False)
    assert np.max(np.abs(np.asarray(r.s, np.float64) - s_ref)) / s_ref[0] < 2e-6


def test_mixed_bulk_mode_validation():
    """Loud rejection of unsatisfiable mixed_bulk combinations: non-f32
    input, collision with bulk_bf16, non-Pallas pair solver. Auto must
    yield to an explicit bulk_bf16=True instead of raising."""
    rng = np.random.default_rng(13)
    a32 = jnp.asarray(rng.standard_normal((96, 96)), jnp.float32)
    with pytest.raises(ValueError, match="float32"):
        sj.svd(a32.astype(jnp.bfloat16),
               config=SVDConfig(mixed_bulk=True, pair_solver="pallas"))
    with pytest.raises(ValueError, match="exclusive"):
        sj.svd(a32, config=SVDConfig(mixed_bulk=True, bulk_bf16=True))
    with pytest.raises(ValueError, match="mixed_bulk"):
        sj.svd(a32, config=SVDConfig(mixed_bulk=True, pair_solver="hybrid"))
    r = sj.svd(a32, config=SVDConfig(bulk_bf16=True))  # auto yields
    assert np.isfinite(np.asarray(r.s)).all()


def test_abs_criterion_pallas_validation():
    """Loud rejection of criterion="abs" + pair_solver="pallas" (the
    kernel measures only the rel statistic; this used to silently rewrite
    to "rel" — VERDICT weak #5). pair_solver="auto" must instead route an
    abs request to a compatible XLA solver, not raise."""
    rng = np.random.default_rng(22)
    a = jnp.asarray(rng.standard_normal((96, 96)), jnp.float32)
    with pytest.raises(ValueError, match="criterion='abs'"):
        sj.svd(a, config=SVDConfig(pair_solver="pallas", criterion="abs"))
    with pytest.raises(ValueError, match="criterion='abs'"):
        solver.SweepStepper(a, config=SVDConfig(pair_solver="pallas",
                                                criterion="abs"))
    # The blocked-rotation lane terminates on the same rel statistic (its
    # abs statistic is an internal bulk control, not the convergence
    # contract): an explicit abs request must raise the SAME way, on
    # every dispatch surface — fused, stepper, batched.
    with pytest.raises(ValueError, match="criterion='abs'"):
        sj.svd(a, config=SVDConfig(pair_solver="block_rotation",
                                   criterion="abs"))
    with pytest.raises(ValueError, match="criterion='abs'"):
        solver.SweepStepper(a, config=SVDConfig(
            pair_solver="block_rotation", criterion="abs"))
    with pytest.raises(ValueError, match="criterion='abs'"):
        solver.svd_batched(a[None], config=SVDConfig(
            pair_solver="block_rotation", criterion="abs"))
    # auto + abs: picks an abs-capable solver and converges.
    r = sj.svd(a, config=SVDConfig(criterion="abs"))
    assert r.status_enum().name == "OK"


def test_split_bf16_not_folded():
    """The x3 split must survive XLA: the naive cast-round-trip form was
    constant-folded to zero (verified on-chip), silently degrading every
    x3 product to one bf16 pass. Guard the bit-mask form."""
    rng = np.random.default_rng(14)
    x = jnp.asarray(rng.standard_normal((64, 64)), jnp.float32)
    q = jnp.asarray(np.linalg.qr(rng.standard_normal((64, 64)))[0],
                    jnp.float32)
    hi = jax.jit(lambda x, q: rounds._einsum(x[None], q[None], "kmi,kij->kmj"))(x, q)
    x3 = jax.jit(lambda x, q: rounds._einsum(x[None], q[None], "kmi,kij->kmj",
                                             x3=True))(x, q)
    b1 = jax.jit(lambda x, q: rounds._einsum(x[None], q[None], "kmi,kij->kmj",
                                             bf16=True))(x, q)
    scale = float(jnp.max(jnp.abs(hi)))
    err_x3 = float(jnp.max(jnp.abs(x3 - hi))) / scale
    err_b1 = float(jnp.max(jnp.abs(b1 - hi))) / scale
    assert err_x3 < 1e-4          # eps_bf16^2 class
    assert err_x3 < err_b1 / 10   # and far below the single-pass error


def test_sweepstepper_kernel_path():
    """The host-stepped SweepStepper must run the SAME Pallas kernel sweeps
    as the fused solver (VERDICT r3 weak #3: checkpointed/instrumented runs
    silently downgraded to the ~5x-slower hybrid XLA solvers), with the
    fused path's preconditioned bookkeeping and sigma refinement."""
    rng = np.random.default_rng(21)
    a = jnp.asarray(rng.standard_normal((160, 96)), jnp.float32)
    st = solver.SweepStepper(a)
    assert st._kernel_path and st.method == "pallas"
    state = st.init()
    while st.should_continue(state):
        state = st.step(state)
    r = st.finish(state)
    a64 = np.asarray(a, np.float64)
    s_ref = np.linalg.svd(a64, compute_uv=False)
    assert np.max(np.abs(np.asarray(r.s, np.float64) - s_ref)) / s_ref[0] < 1e-6
    res = np.linalg.norm(np.asarray(r.u, np.float64)
                         * np.asarray(r.s, np.float64)
                         @ np.asarray(r.v, np.float64).T - a64)
    assert res / np.linalg.norm(a64) < 5e-6
    # Sweep-count parity with the fused solve (same kernels, same loop).
    fused = sj.svd(a)
    assert abs(int(r.sweeps) - int(fused.sweeps)) <= 1


def test_sweepstepper_kernel_path_checkpoint_resume(tmp_path):
    """Kill-and-resume through the checkpoint API stays on the kernel path
    and converges (resume recomputes the deterministic QR preconditioner
    rather than snapshotting it)."""
    from svd_jacobi_tpu.utils import checkpoint
    rng = np.random.default_rng(22)
    a = jnp.asarray(rng.standard_normal((96, 96)), jnp.float32)
    path = tmp_path / "ck.npz"
    st = solver.SweepStepper(a)
    assert st._kernel_path
    state = st.step(st.step(st.init()))
    checkpoint.save_state(path, st, state)
    r = checkpoint.svd_checkpointed(a, path=path)
    s_ref = np.linalg.svd(np.asarray(a, np.float64), compute_uv=False)
    assert np.max(np.abs(np.asarray(r.s, np.float64) - s_ref)) / s_ref[0] < 1e-6
    assert not path.exists()


def test_sweepstepper_kernel_path_rejects_fused_only_modes():
    rng = np.random.default_rng(23)
    a = jnp.asarray(rng.standard_normal((96, 96)), jnp.float32)
    with pytest.raises(ValueError, match="fused-solver"):
        solver.SweepStepper(a, config=SVDConfig(mixed_bulk=True))
    with pytest.raises(ValueError, match="host-stepped"):
        solver.SweepStepper(a, config=SVDConfig(precondition="double"))
