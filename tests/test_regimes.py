"""Target-regime coverage: the b=128 TPU default block path, and
stall/conditioning sweeps across dtype that pin the solver's measured
convergence constants (VERDICT r2 weak #4: the default TPU block path and
the stall-detection constants were untested)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import svd_jacobi_tpu as sj
from svd_jacobi_tpu.config import SVDConfig
from svd_jacobi_tpu.ops import rounds
from svd_jacobi_tpu import solver

HI = jax.lax.Precision.HIGHEST


def test_default_block_size_is_128_for_large_n():
    assert SVDConfig().pick_block_size(2048) == 128
    assert SVDConfig().pick_block_size(65536) == 128
    b, k = solver._plan(2048, 1, SVDConfig())
    assert b == 128 and 2 * k * b == 2048


def test_b128_sweep_path():
    """One kernel sweep at the TPU-default b=128 block width (n = 1024
    columns in 8 blocks, small m so CPU-interpret stays fast): couplings
    must contract and the block stacks keep their shapes."""
    rng = np.random.default_rng(0)
    m, b, k = 48, 128, 4
    top = jnp.asarray(rng.standard_normal((k, m, b)), jnp.float32)
    bot = jnp.asarray(rng.standard_normal((k, m, b)), jnp.float32)
    dmax2 = rounds._global_dmax2(top, bot)
    t2, b2, _, _, off = rounds.sweep(
        top, bot, None, None, dmax2, 0.0, interpret=True, polish=True,
        bf16_gram=False)
    assert t2.shape == top.shape and b2.shape == bot.shape
    # rank m << n: most couplings cannot be resolved in one sweep, but the
    # sweep must make progress on the Gram off-diagonal mass
    x0 = jnp.concatenate([jnp.concatenate([top, bot], axis=0)[i] for i in range(2 * k)], axis=1)
    x1 = jnp.concatenate([jnp.concatenate([t2, b2], axis=0)[i] for i in range(2 * k)], axis=1)

    def offmass(x):
        g = jnp.einsum("mi,mj->ij", x, x, precision=HI)
        return float(jnp.linalg.norm(g * (1 - jnp.eye(g.shape[0]))))

    assert offmass(x1) < offmass(x0)
    assert float(off) > 0.0


@pytest.mark.parametrize("dtype,cond,serr_tol", [
    (jnp.float32, 1e-5, 5e-6),
    (jnp.float32, 1e-2, 5e-6),
    (jnp.bfloat16, 1e-2, 3e-2),
])
def test_conditioning_sweep_pallas(dtype, cond, serr_tol):
    """Graded spectra across dtype: the solve must terminate well under the
    sweep cap (stall detection / tol constants) with sigma error at the
    dtype's floor and live U columns orthogonal."""
    rng = np.random.default_rng(1)
    n = 96
    s_true = np.geomspace(1.0, cond, n)
    q1, _ = np.linalg.qr(rng.standard_normal((n, n)))
    q2, _ = np.linalg.qr(rng.standard_normal((n, n)))
    a = jnp.asarray(q1 * s_true @ q2.T, dtype)
    cfg = SVDConfig(max_sweeps=32)
    r = sj.svd(a, config=cfg)
    assert int(r.sweeps) < 28          # terminated, not budget-exhausted
    sn = np.asarray(r.s, np.float64)
    s_ref = np.linalg.svd(np.asarray(a, np.float64), compute_uv=False)
    assert np.max(np.abs(sn - s_ref)) / s_ref[0] < serr_tol
    # live columns (sigma above the dtype floor) of U stay orthogonal
    eps = float(jnp.finfo(dtype).eps)
    live = sn > 10 * eps * sn[0]
    un = np.asarray(r.u, np.float64)[:, live]
    gram = un.T @ un
    assert np.max(np.abs(gram - np.eye(gram.shape[0]))) < 50 * np.sqrt(n) * eps


@pytest.mark.parametrize("shape,cu,cv,full", [
    ((96, 96), True, True, False),
    ((160, 96), True, True, True),
    ((96, 96), True, False, False),
    ((96, 96), False, True, False),
])
def test_precondition_double(shape, cu, cv, full):
    """dgejsv-style double preconditioning (second QR, inverted U/V
    bookkeeping: the rotation product becomes V, the normalized columns
    become U) must match the single-precondition accuracy for every
    compute_u/compute_v/full_matrices combination."""
    rng = np.random.default_rng(8)
    m, n = shape
    s_true = np.geomspace(1.0, 1e-3, n)
    q1, _ = np.linalg.qr(rng.standard_normal((m, m)))
    q2, _ = np.linalg.qr(rng.standard_normal((n, n)))
    a = jnp.asarray(q1[:, :n] * s_true @ q2.T, jnp.float32)
    a64 = np.asarray(a, np.float64)
    r = sj.svd(a, config=SVDConfig(precondition="double",
                                   pair_solver="pallas"),
               compute_u=cu, compute_v=cv, full_matrices=full)
    s_ref = np.linalg.svd(a64, compute_uv=False)
    assert np.max(np.abs(np.asarray(r.s, np.float64) - s_ref)) / s_ref[0] < 5e-6
    assert (r.u is None) == (not cu) and (r.v is None) == (not cv)
    if cu:
        u = np.asarray(r.u, np.float64)
        assert u.shape == ((m, m) if full else (m, n))
        assert np.max(np.abs(u.T @ u - np.eye(u.shape[1]))) < 5e-5
    if cv:
        v = np.asarray(r.v, np.float64)
        assert np.max(np.abs(v.T @ v - np.eye(n))) < 5e-5
    if cu and cv:
        u = np.asarray(r.u, np.float64)[:, :n]
        res = np.linalg.norm(u * np.asarray(r.s, np.float64)
                             @ np.asarray(r.v, np.float64).T - a64)
        assert res / np.linalg.norm(a64) < 5e-6


@pytest.mark.parametrize("method", ["hybrid", "qr-svd"])
def test_conditioning_sweep_xla_paths(method):
    """The XLA block-solver paths (used by the sharded solver) under a
    graded spectrum: the measured stall/tol constants in
    solver._should_continue must terminate them without exhausting the
    budget or losing sigma accuracy."""
    rng = np.random.default_rng(2)
    n = 48
    s_true = np.geomspace(1.0, 1e-5, n)
    q1, _ = np.linalg.qr(rng.standard_normal((n, n)))
    q2, _ = np.linalg.qr(rng.standard_normal((n, n)))
    a = jnp.asarray(q1 * s_true @ q2.T, jnp.float32)
    r = sj.svd(a, config=SVDConfig(pair_solver=method, max_sweeps=32))
    assert int(r.sweeps) < 28
    sn = np.asarray(r.s, np.float64)
    s_ref = np.linalg.svd(np.asarray(a, np.float64), compute_uv=False)
    assert np.max(np.abs(sn - s_ref)) / s_ref[0] < 5e-6
