"""Single-device solver vs the numpy/LAPACK oracle (SURVEY.md section 4:
sigma oracle + residual + the orthogonality checks the reference lacks)."""

import numpy as np
import jax.numpy as jnp
import pytest

from svd_jacobi_tpu import SVDConfig, svd
from svd_jacobi_tpu.utils import matgen, validation


def _check(a, result, sigma_tol, res_tol, orth_tol=None):
    # orth_tol defaults: the solver's off-norm floor is ~2000*eps (f64) /
    # ~1000*eps (f32); U/V orthogonality errors scale with n * floor.
    if orth_tol is None:
        orth_tol = 1e-10 if result.s.dtype == np.float64 else 5e-3
    s_ref = np.linalg.svd(np.asarray(a, np.float64), compute_uv=False)
    rep = validation.validate(a, result, s_ref=s_ref)
    assert float(rep.sigma_err) < sigma_tol, rep.as_dict()
    if rep.residual_rel is not None:
        assert float(rep.residual_rel) < res_tol, rep.as_dict()
        assert float(rep.u_orth) < orth_tol, rep.as_dict()
        assert float(rep.v_orth) < orth_tol, rep.as_dict()
    # descending order
    s = np.asarray(result.s)
    assert np.all(np.diff(s) <= 1e-30 + 1e-6 * s[0])


@pytest.mark.parametrize("n,b", [(8, 1), (16, 2), (32, 4), (64, 8), (96, 16)])
def test_square_f64(n, b):
    a = matgen.random_dense(n, n, dtype=jnp.float64, seed=n)
    r = svd(a, config=SVDConfig(block_size=b))
    assert int(r.sweeps) < 32
    _check(a, r, sigma_tol=1e-12, res_tol=1e-13)


def test_square_f32():
    a = matgen.random_dense(48, 48, dtype=jnp.float32, seed=3)
    r = svd(a, config=SVDConfig(block_size=8))
    _check(a, r, sigma_tol=1e-5, res_tol=1e-5)


@pytest.mark.parametrize("m,n", [(40, 24), (65, 33), (128, 16)])
def test_tall_skinny(m, n):
    a = matgen.random_dense(m, n, dtype=jnp.float64, seed=m + n)
    r = svd(a, config=SVDConfig(block_size=4))
    _check(a, r, sigma_tol=1e-12, res_tol=1e-13)


def test_wide_via_transpose():
    a = matgen.random_dense(20, 50, dtype=jnp.float64, seed=7)
    r = svd(a, config=SVDConfig(block_size=4))
    s_ref = np.linalg.svd(np.asarray(a), compute_uv=False)
    np.testing.assert_allclose(np.asarray(r.s), s_ref, rtol=1e-10, atol=1e-12)
    assert r.u.shape == (20, 20) and r.v.shape == (50, 20)
    rep = validation.validate(a, r)
    assert float(rep.residual_rel) < 1e-13


def test_odd_n_padding():
    a = matgen.random_dense(31, 29, dtype=jnp.float64, seed=11)
    r = svd(a, config=SVDConfig(block_size=4))
    _check(a, r, sigma_tol=1e-12, res_tol=1e-13)


def test_upper_triangular_reference_input():
    """The reference's benchmark input: seeded upper-triangular (main.cu:1558).

    Random triangular matrices are numerically singular (cond ~ 1e17 here):
    U columns for numerically-null sigmas are noise by construction (same as
    one-sided Jacobi everywhere, incl. the reference's U = A*inv(Sigma),
    lib/JacobiMethods.cu:1156-1173), so orthogonality is only checked on the
    numerically live columns.
    """
    n = 64
    a = matgen.random_upper_triangular(n, dtype=jnp.float64)
    r = svd(a, config=SVDConfig(block_size=8))
    assert int(r.sweeps) < 20
    s_ref = np.linalg.svd(np.asarray(a), compute_uv=False)
    rep = validation.validate(a, r, s_ref=s_ref)
    assert float(rep.sigma_err) < 1e-12
    assert float(rep.residual_rel) < 1e-13
    assert float(rep.v_orth) < 1e-10
    s = np.asarray(r.s)
    live = s > s[0] * n * np.finfo(np.float64).eps * 10
    u_live = np.asarray(r.u)[:, live]
    assert np.abs(u_live.T @ u_live - np.eye(live.sum())).max() < 1e-9


def test_known_spectrum():
    s_true = np.geomspace(1.0, 1e-4, 24)
    a = matgen.with_known_spectrum(48, 24, s_true, dtype=jnp.float64)
    r = svd(a, config=SVDConfig(block_size=4))
    np.testing.assert_allclose(np.asarray(r.s), s_true, rtol=1e-10, atol=1e-12)


def test_novec_options():
    a = matgen.random_dense(24, 24, dtype=jnp.float64, seed=5)
    r = svd(a, compute_u=False, compute_v=False, config=SVDConfig(block_size=4))
    assert r.u is None and r.v is None
    s_ref = np.linalg.svd(np.asarray(a), compute_uv=False)
    np.testing.assert_allclose(np.asarray(r.s), s_ref, rtol=1e-10, atol=1e-12)
    r2 = svd(a, compute_u=True, compute_v=False, config=SVDConfig(block_size=4))
    assert r2.u is not None and r2.v is None


def test_full_matrices():
    a = matgen.random_dense(40, 12, dtype=jnp.float64, seed=9)
    r = svd(a, full_matrices=True, config=SVDConfig(block_size=4))
    assert r.u.shape == (40, 40)
    rep = validation.validate(a, type(r)(u=r.u[:, :12], s=r.s, v=r.v,
                                         sweeps=r.sweeps, off_rel=r.off_rel))
    assert float(rep.residual_rel) < 1e-13
    assert float(validation.orthogonality_error(r.u)) < 1e-12


def test_rank_deficient():
    a = matgen.with_known_spectrum(30, 20, np.r_[np.ones(10), np.zeros(10)],
                                   dtype=jnp.float64)
    r = svd(a, config=SVDConfig(block_size=4))
    s = np.asarray(r.s)
    np.testing.assert_allclose(s[:10], 1.0, rtol=1e-10)
    assert np.all(s[10:] < 1e-10)
    rep = validation.validate(a, r)
    assert float(rep.residual_rel) < 1e-12


def test_tiny_and_degenerate():
    for m, n in [(1, 1), (2, 1), (3, 2), (2, 3)]:
        a = matgen.random_dense(m, n, dtype=jnp.float64, seed=m * 10 + n)
        r = svd(a)
        s_ref = np.linalg.svd(np.asarray(a), compute_uv=False)
        np.testing.assert_allclose(np.asarray(r.s), s_ref, rtol=1e-10, atol=1e-12)
