"""Warm-started solves (`solver.svd(v0=...)` / `solver.svd_update`):
the don't-recompute-what-you-know lane of ROADMAP "Two-phase lazy-vector
serving + streaming updates".

The load-bearing regression here is the SWEEP-COUNT pin (PROFILE.md
item 27 / item 4's quadratic-convergence class): a rank-1-perturbed 512²
input warm-started from the prior right factor converges in <= 3 sweeps
where a cold solve takes >= 8, on BOTH the Pallas(-interpret) kernel
lane and the XLA block lane. Correctness is the existing convergence
criterion's — the factor composition V = V0 @ W is exact — so the rest
of the file pins the API contract (orientation handling, validation,
graceful degradation on an unrelated v0).
"""

import numpy as np
import pytest

import jax.numpy as jnp

from svd_jacobi_tpu import SVDConfig, solver
from svd_jacobi_tpu.solver import SolveStatus


def _rank1_pair(n=512, seed=42, scale=0.01, dtype=np.float32):
    rng = np.random.default_rng(seed)
    a = rng.standard_normal((n, n)).astype(dtype)
    u1 = rng.standard_normal((n, 1)).astype(dtype)
    v1 = rng.standard_normal((1, n)).astype(dtype)
    return a, (a + scale * (u1 @ v1) / n).astype(dtype)


def _resid(r, a):
    return np.abs(np.asarray(r.u) @ np.diag(np.asarray(r.s))
                  @ np.asarray(r.v).T - np.asarray(a)).max()


class TestWarmStartSweepContract:
    """The measured claim behind the whole warm-start lane, pinned on
    both solver lanes: 'pallas' is the (interpret-mode on CPU) kernel
    path, 'qr-svd' the XLA block path."""

    @pytest.mark.parametrize("method", ["pallas", "qr-svd"])
    def test_rank1_perturbed_512_converges_in_3_sweeps(self, method):
        cfg = SVDConfig(pair_solver=method)
        a, a_new = _rank1_pair()
        prior = solver.svd(jnp.asarray(a), config=cfg)
        assert prior.status_enum() is SolveStatus.OK
        cold = solver.svd(jnp.asarray(a_new), config=cfg)
        warm = solver.svd_update(prior, jnp.asarray(a_new), config=cfg)
        assert warm.status_enum() is SolveStatus.OK
        assert int(cold.sweeps) >= 8, (
            f"cold solve converged in {int(cold.sweeps)} sweeps — the "
            f"fixture no longer exercises the warm-start win")
        assert int(warm.sweeps) <= 3, (
            f"warm start took {int(warm.sweeps)} sweeps (cold: "
            f"{int(cold.sweeps)}) — the PROFILE item 27 convergence "
            f"contract regressed")
        # Same answer, to the solve's own accuracy class.
        assert _resid(warm, a_new) < 5e-5
        np.testing.assert_allclose(
            np.asarray(warm.s), np.asarray(cold.s), rtol=1e-4, atol=1e-4)


class TestWarmStartAPI:
    CFG = SVDConfig(pair_solver="qr-svd")

    def test_v0_composition_is_exact(self):
        a, a_new = _rank1_pair(n=96, seed=7)
        prior = solver.svd(jnp.asarray(a), config=self.CFG)
        warm = solver.svd(jnp.asarray(a_new), v0=prior.v, config=self.CFG)
        assert _resid(warm, a_new) < 1e-4
        # V is orthonormal after composition (V = V0 @ W, both factors
        # orthonormal).
        v = np.asarray(warm.v)
        np.testing.assert_allclose(v.T @ v, np.eye(v.shape[1]), atol=1e-4)

    def test_wide_update_transposes_through_prior_u(self):
        a, a_new = _rank1_pair(n=80, seed=9)
        a_w, a_new_w = a[:60].copy(), a_new[:60].copy()   # (60, 80) wide
        prior = solver.svd(jnp.asarray(a_w), config=self.CFG)
        warm = solver.svd_update(prior, jnp.asarray(a_new_w),
                                 config=self.CFG)
        assert np.asarray(warm.u).shape == (60, 60)
        assert np.asarray(warm.v).shape == (80, 60)
        assert _resid(warm, a_new_w) < 1e-4

    def test_unrelated_v0_still_correct_just_slow(self):
        """Correctness never depends on HOW near the warm start is: an
        unrelated orthonormal v0 converges cold-slow but exactly."""
        a, _ = _rank1_pair(n=64, seed=11)
        q, _ = np.linalg.qr(np.random.default_rng(3).standard_normal(
            (64, 64)).astype(np.float32))
        warm = solver.svd(jnp.asarray(a), v0=jnp.asarray(q),
                          config=self.CFG)
        assert warm.status_enum() is SolveStatus.OK
        assert _resid(warm, a) < 1e-4

    def test_v0_shape_and_orientation_validation(self):
        a, _ = _rank1_pair(n=48, seed=13)
        with pytest.raises(ValueError, match="right factor"):
            solver.svd(jnp.asarray(a), v0=jnp.zeros((24, 24)))
        with pytest.raises(ValueError, match="tall"):
            # (24, 48) wide input: direct v0 warm starts require m >= n.
            solver.svd(jnp.asarray(a[:24]),
                       v0=jnp.eye(48, dtype=jnp.float32))

    def test_update_requires_prior_factor(self):
        a, a_new = _rank1_pair(n=48, seed=17)
        prior = solver.svd(jnp.asarray(a), compute_v=False,
                           config=self.CFG)
        with pytest.raises(ValueError, match="prior"):
            solver.svd_update(prior, jnp.asarray(a_new), config=self.CFG)

    def test_stepper_v0_finish_composes(self):
        from svd_jacobi_tpu.solver import SweepStepper
        a, a_new = _rank1_pair(n=64, seed=19)
        prior = solver.svd(jnp.asarray(a), config=self.CFG)
        st = SweepStepper(jnp.asarray(a_new), v0=prior.v, config=self.CFG)
        state = st.init()
        while st.should_continue(state):
            state = st.step(state)
        r = st.finish(state)
        assert int(r.sweeps) <= 4    # near-diagonal entry
        assert _resid(r, a_new) < 1e-4
