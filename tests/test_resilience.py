"""The resilience subsystem: in-graph solve health (SVDResult.status),
guarded inputs, the retry/escalation ladder, hardened checkpointing, and
the deterministic fault-injection (`-m chaos`) lane.

What is actually being proven:

  * the fused loops' health word detects NaN poisoning that the deflation
    mask would otherwise hide — an injected NaN yields status=NONFINITE,
    never a silent OK — on the single-device, hybrid-XLA, and mesh paths;
  * `resilient_svd` walks the escalation ladder from a bad status back to
    a residual-correct solve, records the episode as a schema-valid
    ``retry`` manifest record, and fails fast on unrecoverable inputs;
  * extreme-scale inputs (Gram-path overflow/underflow) are power-of-two
    pre-scaled and the scale is undone exactly on sigma;
  * corrupt snapshots (truncated, bit-flipped, wrong fingerprint) are
    detected, QUARANTINED, and the solve resumes from the rotated
    previous generation to the same sigmas as an uninterrupted run;
  * a SIGTERM mid-solve triggers one final snapshot and a later plain
    re-run resumes from exactly the killed sweep (subprocess, real
    signal);
  * the multi-process save barrier times out instead of hanging, and the
    coordinator connect retries transient refusals with backoff.
"""

import json
import os
import shutil
import signal
import subprocess
import sys
import time
import warnings
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import svd_jacobi_tpu as sj
from svd_jacobi_tpu import SolveStatus, SVDConfig
from svd_jacobi_tpu.resilience import chaos, guard
from svd_jacobi_tpu.solver import SweepStepper
from svd_jacobi_tpu.utils import checkpoint, matgen, validation


def _ref(a):
    return np.linalg.svd(np.asarray(a, np.float64), compute_uv=False)


class TestStatusWord:
    def test_ok_on_converged_paths(self, eight_devices):
        from svd_jacobi_tpu.parallel import sharded
        a = matgen.random_dense(96, 96, seed=7, dtype=jnp.float32)
        assert sj.svd(a).status_enum() == SolveStatus.OK          # pallas
        assert sj.svd(a, config=SVDConfig(pair_solver="hybrid")
                      ).status_enum() == SolveStatus.OK           # xla hybrid
        assert sharded.svd(a).status_enum() == SolveStatus.OK     # mesh
        a64 = matgen.random_dense(48, 48, seed=3, dtype=jnp.float64)
        assert sj.svd(a64).status_enum() == SolveStatus.OK        # f64 qr-svd

    def test_max_sweeps_exhaustion(self):
        a = matgen.random_dense(96, 96, seed=7, dtype=jnp.float32)
        r = sj.svd(a, config=SVDConfig(max_sweeps=2))
        assert r.status_enum() == SolveStatus.MAX_SWEEPS
        assert int(r.sweeps) == 2

    def test_status_rides_transpose(self):
        a = matgen.random_dense(32, 64, seed=5, dtype=jnp.float32)
        assert sj.svd(a).status is not None
        assert sj.svd(a).status_enum() == SolveStatus.OK

    def test_stepper_reports_status(self):
        a = matgen.random_dense(48, 48, seed=9, dtype=jnp.float64)
        st = SweepStepper(a, config=SVDConfig(block_size=4))
        state = st.init()
        while st.should_continue(state):
            state = st.step(state)
        assert st.finish(state).status_enum() == SolveStatus.OK

    def test_stepper_detects_nan_input(self):
        """The deflation mask hides NaN columns from the masked off-norm;
        the finish-time probe must catch the poisoned stacks anyway."""
        bad = np.asarray(
            matgen.random_dense(48, 48, seed=9, dtype=jnp.float64)).copy()
        bad[5, 5] = np.nan
        st = SweepStepper(jnp.asarray(bad), config=SVDConfig(block_size=4))
        state, n = st.init(), 0
        while st.should_continue(state) and n < 64:
            state, n = st.step(state), n + 1
        assert st.finish(state).status_enum() == SolveStatus.NONFINITE


@pytest.mark.chaos
class TestChaosNanInjection:
    """Acceptance: injected NaN at sweep 3 yields NONFINITE — never OK."""

    def test_fused_pallas_path(self):
        a = matgen.random_dense(96, 96, seed=7, dtype=jnp.float32)
        with chaos.nan_at_sweep(3):
            r = sj.svd(a)
        assert r.status_enum() == SolveStatus.NONFINITE
        # The loop also stops promptly instead of sweeping NaNs to budget.
        assert int(r.sweeps) <= 5

    def test_fused_xla_hybrid_path(self):
        a = matgen.random_dense(96, 96, seed=7, dtype=jnp.float32)
        with chaos.nan_at_sweep(2):
            r = sj.svd(a, config=SVDConfig(pair_solver="hybrid"))
        assert r.status_enum() == SolveStatus.NONFINITE

    def test_fused_mesh_path(self, eight_devices):
        from svd_jacobi_tpu.parallel import sharded
        a = matgen.random_dense(96, 96, seed=7, dtype=jnp.float32)
        with chaos.nan_at_sweep(3):
            r = sharded.svd(a)
        assert r.status_enum() == SolveStatus.NONFINITE

    def test_unarmed_after_shots_consumed(self):
        a = matgen.random_dense(96, 96, seed=7, dtype=jnp.float32)
        with chaos.nan_at_sweep(3, shots=1):
            assert sj.svd(a).status_enum() == SolveStatus.NONFINITE
            # Second dispatch inside the context: shot budget spent.
            assert sj.svd(a).status_enum() == SolveStatus.OK
        assert sj.svd(a).status_enum() == SolveStatus.OK


class TestGuardedInputs:
    def test_nonfinite_input_raises(self):
        bad = np.ones((16, 16), np.float32)
        bad[3, 4] = np.inf
        with pytest.raises(guard.NonFiniteInputError):
            sj.resilience.resilient_svd(jnp.asarray(bad))

    def test_prescale_is_exact_power_of_two(self):
        a = matgen.random_dense(32, 32, seed=4, dtype=jnp.float32)
        scaled, p = guard.prescale(a * jnp.float32(1e30))
        assert p != 0
        back = guard.unscale_sigma(scaled, p)
        np.testing.assert_array_equal(np.asarray(back),
                                      np.asarray(a * jnp.float32(1e30)))

    def test_safe_scale_untouched(self):
        a = matgen.random_dense(32, 32, seed=4, dtype=jnp.float32)
        scaled, p = guard.prescale(a)
        assert p == 0 and scaled is a

    def test_resilient_svd_recovers_gram_overflow(self):
        """1e30-scale f32 input: sigma^2 overflows the Gram path (the raw
        solve reads NONFINITE); the guard pre-scales and the sigmas match
        the oracle after the exact undo."""
        a = matgen.random_dense(64, 64, seed=11, dtype=jnp.float32)
        big = a * jnp.float32(1e30)
        assert sj.svd(big).status_enum() == SolveStatus.NONFINITE
        r, rep = sj.resilience.resilient_svd(big, return_report=True)
        assert rep["final_status"] == "OK" and rep["scale_pow2"] != 0
        s_ref = _ref(a) * 1e30
        assert (np.max(np.abs(np.asarray(r.s, np.float64) - s_ref))
                / s_ref[0]) < 1e-5


@pytest.mark.chaos
class TestEscalation:
    def test_recovers_injected_nan_to_residual(self, tmp_path):
        """Acceptance: resilient_svd takes a NONFINITE first attempt back
        to residual < tol via the ladder, and records the episode."""
        a = matgen.random_dense(96, 96, seed=7, dtype=jnp.float32)
        mpath = tmp_path / "manifest.jsonl"
        with chaos.nan_at_sweep(3, shots=1):
            r, rep = sj.resilience.resilient_svd(
                a, return_report=True, manifest_path=mpath)
        assert rep["attempts"][0]["status"] == "NONFINITE"
        assert rep["final_status"] == "OK"
        assert r.status_enum() == SolveStatus.OK
        v = validation.validate(a, r)
        assert float(v.residual_rel) < 1e-4
        # Schema-valid "retry" record in the manifest stream.
        from svd_jacobi_tpu.obs import manifest
        recs = manifest.load(mpath)
        assert [rec["kind"] for rec in recs] == ["retry"]
        manifest.validate(recs[0])
        assert recs[0]["final_status"] == "OK"
        assert [at["rung"] for at in recs[0]["attempts"]
                ][0] == "base"
        assert "retry episode" in manifest.summarize(recs[0])

    def test_no_retry_when_first_attempt_ok(self):
        a = matgen.random_dense(64, 64, seed=2, dtype=jnp.float32)
        r, rep = sj.resilience.resilient_svd(a, return_report=True)
        assert len(rep["attempts"]) == 1
        assert rep["attempts"][0]["rung"] == "base"

    def test_ladder_is_bounded_and_ends_at_lapack(self):
        """max_sweeps=1 starves every Jacobi rung (MAX_SWEEPS each); the
        ladder must walk its full bounded length and land on the
        LAPACK-class fallback, which succeeds."""
        a = matgen.random_dense(64, 64, seed=2, dtype=jnp.float32)
        r, rep = sj.resilience.resilient_svd(
            a, config=SVDConfig(max_sweeps=1), return_report=True)
        rungs = [at["rung"] for at in rep["attempts"]]
        assert rungs[-1] == "lapack_gesvd"
        assert all(at["status"] == "MAX_SWEEPS"
                   for at in rep["attempts"][:-1])
        assert rep["final_status"] == "OK"
        s_ref = _ref(a)
        assert (np.max(np.abs(np.asarray(r.s, np.float64) - s_ref))
                / s_ref[0]) < 1e-5

    def test_max_attempts_bounds_the_ladder(self):
        a = matgen.random_dense(64, 64, seed=2, dtype=jnp.float32)
        r, rep = sj.resilience.resilient_svd(
            a, config=SVDConfig(max_sweeps=1), max_attempts=2,
            return_report=True)
        assert len(rep["attempts"]) == 2
        assert rep["final_status"] == "MAX_SWEEPS"
        assert r.status_enum() == SolveStatus.MAX_SWEEPS

    def test_ladder_watchdog_fires_on_overrun(self, tmp_path):
        """Satellite: the uncancellable ladder's wall-clock watchdog. An
        episode that runs past ``watchdog_s`` records a ladder_overrun
        fleet event and calls on_overrun (the fleet's lane-unhealthy
        hook) — WITHOUT aborting the ladder, which still returns its
        honest result."""
        import time as _time

        from svd_jacobi_tpu.obs import manifest
        path = tmp_path / "manifest.jsonl"
        fired = []
        a = matgen.random_dense(48, 48, seed=9, dtype=jnp.float32)
        r, rep = sj.resilience.resilient_svd(
            a, config=SVDConfig(max_sweeps=1),   # starves Jacobi rungs:
            manifest_path=str(path),             # a multi-attempt episode
            watchdog_s=0.0, on_overrun=fired.append,
            return_report=True)
        deadline = _time.monotonic() + 5.0
        while not fired and _time.monotonic() < deadline:
            _time.sleep(0.01)   # the timer thread races the short ladder
        assert rep["watchdog_overrun"] is True
        assert len(fired) == 1 and fired[0]["budget_s"] == 0.0
        assert fired[0]["m"] == 48
        # The ladder was NOT aborted: it still walked to a result.
        assert rep["final_status"] == "OK"
        kinds = [rec["kind"] for rec in manifest.load(path)]
        assert "fleet" in kinds and "retry" in kinds
        over = [rec for rec in manifest.load(path)
                if rec["kind"] == "fleet"]
        assert over[0]["event"] == "ladder_overrun"
        manifest.validate(over[0])
        retry = [rec for rec in manifest.load(path)
                 if rec["kind"] == "retry"][0]
        assert retry["watchdog_overrun"] is True

    def test_ladder_watchdog_quiet_within_budget(self):
        a = matgen.random_dense(32, 32, seed=3, dtype=jnp.float32)
        r, rep = sj.resilience.resilient_svd(
            a, watchdog_s=600.0, return_report=True)
        assert rep["watchdog_overrun"] is False
        assert rep["final_status"] == "OK"


CKPT_CFG = SVDConfig(block_size=4)


def _two_generations(a, path):
    """Run two sweeps, snapshotting each — leaves current + rotated."""
    st = SweepStepper(a, config=CKPT_CFG)
    state = st.init()
    state = st.step(state)
    checkpoint.save_state(path, st, state)
    state = st.step(state)
    checkpoint.save_state(path, st, state)
    assert path.exists() and checkpoint._prev_path(path).exists()


@pytest.mark.chaos
class TestCheckpointCorruption:
    """Acceptance: truncated / bit-flipped / wrong-fingerprint snapshots
    are detected, quarantined, and the solve resumes from the rotated
    generation to the uninterrupted sigmas."""

    @pytest.fixture()
    def a64(self):
        return matgen.random_dense(32, 32, seed=8, dtype=jnp.float64)

    @pytest.fixture()
    def s_ref(self, a64, tmp_path):
        r = checkpoint.svd_checkpointed(a64, path=tmp_path / "ref.npz",
                                        config=CKPT_CFG)
        return np.asarray(r.s)

    @pytest.mark.parametrize("mode", ["truncate", "flip", "zero"])
    def test_corrupt_current_falls_back_to_rotated(self, a64, s_ref,
                                                   tmp_path, mode):
        path = tmp_path / "ck.npz"
        _two_generations(a64, path)
        chaos.corrupt_checkpoint(path, mode)
        with pytest.warns(RuntimeWarning, match="quarantined"):
            r = checkpoint.svd_checkpointed(a64, path=path, config=CKPT_CFG)
        assert path.with_name(path.name + ".quarantined").exists()
        np.testing.assert_allclose(np.asarray(r.s), s_ref, rtol=1e-10)

    def test_mismatched_fingerprint_falls_back(self, a64, s_ref, tmp_path):
        path = tmp_path / "ck.npz"
        _two_generations(a64, path)
        # Overwrite the current generation with a snapshot of a DIFFERENT
        # matrix (same layout): fingerprint mismatch, not corruption.
        b = matgen.random_dense(32, 32, seed=99, dtype=jnp.float64)
        stb = SweepStepper(b, config=CKPT_CFG)
        checkpoint.save_state(tmp_path / "other.npz", stb,
                              stb.step(stb.init()))
        shutil.copy(tmp_path / "other.npz", path)
        with pytest.warns(RuntimeWarning, match="quarantined"):
            r = checkpoint.svd_checkpointed(a64, path=path, config=CKPT_CFG)
        np.testing.assert_allclose(np.asarray(r.s), s_ref, rtol=1e-10)

    def test_every_generation_corrupt_raises(self, a64, tmp_path):
        path = tmp_path / "ck.npz"
        _two_generations(a64, path)
        chaos.corrupt_checkpoint(path, "truncate")
        chaos.corrupt_checkpoint(checkpoint._prev_path(path), "flip")
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            with pytest.raises(checkpoint.CheckpointCorruptError):
                checkpoint.svd_checkpointed(a64, path=path, config=CKPT_CFG)

    def test_mismatch_without_fallback_still_rejected(self, a64, tmp_path):
        """The pre-hardening contract: resuming a DIFFERENT solve from a
        single (unrotated) snapshot raises the loud mismatch error."""
        path = tmp_path / "ck.npz"
        st = SweepStepper(a64, config=CKPT_CFG)
        checkpoint.save_state(path, st, st.init())
        b = matgen.random_dense(40, 40, seed=10, dtype=jnp.float64)
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            with pytest.raises(ValueError, match="does not match"):
                checkpoint.svd_checkpointed(b, path=path, config=CKPT_CFG)


class TestCheckpointDurability:
    def test_tmp_removed_on_failure_paths(self, tmp_path):
        with pytest.raises(ZeroDivisionError):
            checkpoint._write_npz_atomic(
                tmp_path / "x.npz", {"a": np.zeros(4)},
                pre_rename=lambda: 1 / 0)
        assert not list(tmp_path.glob("*.tmp"))
        assert not (tmp_path / "x.npz").exists()

    def test_checksum_round_trip(self, tmp_path):
        a = matgen.random_dense(16, 16, seed=1, dtype=jnp.float64)
        st = SweepStepper(a, config=CKPT_CFG)
        state = st.step(st.init())
        path = tmp_path / "ck.npz"
        checkpoint.save_state(path, st, state)
        with np.load(path) as z:
            assert "checksum" in z.files
            checkpoint._verify_checksum(z, path)
        loaded = checkpoint.load_state(
            path, SweepStepper(a, config=CKPT_CFG))
        np.testing.assert_array_equal(np.asarray(loaded.top),
                                      np.asarray(state.top))

    def test_barrier_timeout_raises(self):
        t0 = time.perf_counter()
        with pytest.raises(RuntimeError, match="timed out"):
            checkpoint._run_barrier(lambda: time.sleep(30), 0.2, "test")
        assert time.perf_counter() - t0 < 5.0

    def test_barrier_propagates_errors(self):
        def boom():
            raise RuntimeError("peer exploded")
        with pytest.raises(RuntimeError, match="peer exploded"):
            checkpoint._run_barrier(boom, 5.0, "test")


@pytest.mark.chaos
def test_sigterm_kill_then_resume(tmp_path):
    """Acceptance: a SIGTERM-killed checkpointed solve wrote its final
    snapshot (the production SIGTERM handler, driven by a real signal in a
    subprocess), and a plain re-run resumes from exactly the killed sweep
    to the sigmas of an uninterrupted solve."""
    worker = Path(__file__).parent / "_chaos_worker.py"
    ckpt = tmp_path / "state.npz"
    kill_sweep = 3

    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)  # worker pins cpu via the config API
    env["PYTHONPATH"] = (str(Path(__file__).parent.parent) + os.pathsep
                         + env.get("PYTHONPATH", ""))
    p = subprocess.run(
        [sys.executable, str(worker), str(ckpt), str(kill_sweep)],
        env=env, cwd=str(worker.parent.parent), timeout=280,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
    # Died a SIGTERM death (handler re-delivered the signal after the
    # final snapshot), not a clean exit.
    assert p.returncode == -signal.SIGTERM, p.stdout[-3000:]
    assert ckpt.exists()
    with np.load(ckpt) as z:
        assert int(z["sweeps"]) == kill_sweep  # the SIGTERM-boundary state

    # Resume in THIS process: same matrix from the same seed.
    a = matgen.random_dense(48, 48, seed=33, dtype=jnp.float64)
    r = checkpoint.svd_checkpointed(a, path=ckpt, every=1000,
                                    config=SVDConfig(block_size=4))
    assert int(r.sweeps) > kill_sweep
    assert not ckpt.exists()  # removed on success
    r_ref = checkpoint.svd_checkpointed(a, path=tmp_path / "ref.npz",
                                        every=1000,
                                        config=SVDConfig(block_size=4))
    np.testing.assert_allclose(np.asarray(r.s), np.asarray(r_ref.s),
                               rtol=1e-12, atol=1e-14)


class TestLaunchRetry:
    def test_transient_refusal_retried_with_backoff(self, monkeypatch):
        from svd_jacobi_tpu import _compat
        from svd_jacobi_tpu.parallel import launch
        calls, sleeps = [], []

        def fake_init(**kw):
            calls.append(kw)
            if len(calls) < 3:
                raise RuntimeError(
                    "failed to connect to coordinator: connection refused")

        monkeypatch.setattr(jax.distributed, "initialize", fake_init)
        monkeypatch.setattr(_compat, "distributed_is_initialized",
                            lambda: False)
        monkeypatch.setattr(launch, "_sleep", sleeps.append)
        with pytest.warns(RuntimeWarning, match="retrying"):
            ctx = launch.initialize(coordinator_address="127.0.0.1:1",
                                    num_processes=1, process_id=0)
        assert len(calls) == 3
        # Decorrelated-jitter backoff: every delay obeys the declared
        # bound base <= d <= min(cap, 3 * previous) — no fixed multiples
        # (a fleet restart must not thundering-herd the coordinator).
        assert len(sleeps) == 2
        prev = 0.5
        for d in sleeps:
            assert 0.5 <= d <= min(30.0, 3.0 * prev)
            prev = d
        assert ctx.process_count >= 1

    def test_backoff_delay_bound(self):
        """Satellite regression: the decorrelated-jitter delay is ALWAYS
        within [base, min(cap, 3 * prev)] — over many draws and across
        the cap crossover — and two draws from the same state differ
        (that is the de-synchronization)."""
        from svd_jacobi_tpu.parallel import launch
        draws = []
        prev = 0.5
        for _ in range(200):
            d = launch._backoff_delay(0.5, prev, cap_s=4.0)
            assert 0.5 <= d <= min(4.0, 3.0 * prev)
            draws.append(d)
            prev = d
        # Growth saturates at the cap, never beyond it.
        assert max(draws) <= 4.0
        # Jitter is real: the draws are not a deterministic ladder.
        assert len({round(d, 6) for d in draws}) > 10

    def test_retries_are_bounded(self, monkeypatch):
        from svd_jacobi_tpu import _compat
        from svd_jacobi_tpu.parallel import launch

        def always_refused(**kw):
            raise RuntimeError("connection refused")

        monkeypatch.setattr(jax.distributed, "initialize", always_refused)
        monkeypatch.setattr(_compat, "distributed_is_initialized",
                            lambda: False)
        monkeypatch.setattr(launch, "_sleep", lambda s: None)
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            with pytest.raises(RuntimeError, match="after 3 attempt"):
                launch.initialize(coordinator_address="127.0.0.1:1",
                                  num_processes=1, process_id=0,
                                  connect_retries=2)

    def test_order_error_never_retried(self, monkeypatch):
        from svd_jacobi_tpu import _compat
        from svd_jacobi_tpu.parallel import launch
        calls = []

        def order_error(**kw):
            calls.append(kw)
            raise RuntimeError(
                "jax.distributed.initialize must be called before any JAX "
                "computations")

        monkeypatch.setattr(jax.distributed, "initialize", order_error)
        monkeypatch.setattr(_compat, "distributed_is_initialized",
                            lambda: False)
        with pytest.raises(RuntimeError, match="must be called before"):
            launch.initialize(coordinator_address="127.0.0.1:1",
                              num_processes=1, process_id=0)
        assert len(calls) == 1


class TestCliStatus:
    def test_status_in_report_and_exit_zero(self, tmp_path, capsys):
        from svd_jacobi_tpu import cli
        rc = cli.main(["48", "--dtype", "float64", "--selftest-n", "16",
                       "--report-dir", str(tmp_path)])
        assert rc == 0
        out = capsys.readouterr().out.strip().splitlines()[-1]
        solve = json.loads(out)
        assert solve["status"] == "OK"
        # The manifest record carries it too.
        from svd_jacobi_tpu.obs import manifest
        recs = manifest.load(tmp_path / "manifest.jsonl")
        assert recs[-1]["solve"]["status"] == "OK"

    @pytest.mark.chaos
    def test_nonfinite_solve_exits_nonzero(self, tmp_path, capsys):
        from svd_jacobi_tpu import cli
        with chaos.nan_at_sweep(1, shots=16):
            rc = cli.main(["48", "--matrix", "dense", "--no-selftest",
                           "--report-dir", str(tmp_path)])
        assert rc != 0
        out = capsys.readouterr().out.strip().splitlines()[-1]
        assert json.loads(out)["status"] == "NONFINITE"

    @pytest.mark.chaos
    def test_failed_selftest_exits_nonzero(self, tmp_path, capsys):
        from svd_jacobi_tpu import cli
        with chaos.nan_at_sweep(1, shots=16):
            rc = cli.main(["48", "--matrix", "dense", "--selftest-n", "16",
                           "--report-dir", str(tmp_path)])
        assert rc != 0
