"""Property tests for the tournament schedule (SURVEY.md section 7 step 1:
"every pair exactly once per sweep" carries the proof obligation for both the
single-device scan and the ppermute ring)."""

import itertools

import numpy as np
import jax.numpy as jnp
import pytest

from svd_jacobi_tpu.parallel import schedule as sched


@pytest.mark.parametrize("nblocks", [2, 4, 6, 8, 16, 30, 64])
def test_every_pair_exactly_once(nblocks):
    table = sched.schedule(nblocks)
    assert table.shape == (sched.num_rounds(nblocks), nblocks // 2, 2)
    seen = [tuple(sorted(p)) for rnd in table for p in rnd]
    expect = list(itertools.combinations(range(nblocks), 2))
    assert sorted(seen) == sorted(expect)
    assert len(seen) == len(set(seen))


@pytest.mark.parametrize("nblocks", [2, 4, 8, 12])
def test_rounds_are_disjoint(nblocks):
    for rnd in sched.schedule(nblocks):
        flat = rnd.ravel().tolist()
        assert sorted(flat) == list(range(nblocks))


@pytest.mark.parametrize("nblocks", [4, 8, 10])
def test_rotation_returns_to_start(nblocks):
    """The rotation is a (2k-1)-cycle on non-fixed slots: after 2k-1 steps the
    layout returns to the initial assignment (so sweeps compose cleanly)."""
    k = nblocks // 2
    top, bot = np.arange(k), np.arange(k, 2 * k)
    t, b = top.copy(), bot.copy()
    for _ in range(sched.num_rounds(nblocks)):
        t, b = sched.rotate_indices(t, b)
    np.testing.assert_array_equal(t, top)
    np.testing.assert_array_equal(b, bot)


def test_rotate_blocks_matches_rotate_indices():
    k = 5
    top_i, bot_i = np.arange(k), np.arange(k, 2 * k)
    top_d = jnp.arange(k, dtype=jnp.float32)[:, None, None] * jnp.ones((1, 3, 2))
    bot_d = jnp.arange(k, 2 * k, dtype=jnp.float32)[:, None, None] * jnp.ones((1, 3, 2))
    for _ in range(3):
        top_i, bot_i = sched.rotate_indices(top_i, bot_i)
        top_d, bot_d = sched.rotate_blocks(top_d, bot_d)
    np.testing.assert_array_equal(np.asarray(top_d[:, 0, 0]), top_i)
    np.testing.assert_array_equal(np.asarray(bot_d[:, 0, 0]), bot_i)


def test_single_pair_identity():
    top, bot = np.array([0]), np.array([1])
    t, b = sched.rotate_indices(top, bot)
    np.testing.assert_array_equal(t, top)
    np.testing.assert_array_equal(b, bot)
