"""graftlock (`svd_jacobi_tpu.analysis.concurrency`): CONC001 static
lock discipline, CONC002 runtime lock-graph sanitizer, CONC003
condition-variable discipline.

The fixture corpus under tests/fixtures/conc_violations/ proves every
rule demonstrably fires (with per-fixture LOCK_ORDER declarations); the
real package must lint clean; the chaos soaks run green under the
instrumented locks with an acyclic final acquisition graph; and the
sanitizer is provably zero-cost when off (the OBS002 discipline).
"""

import importlib.util
import threading
from collections import Counter
from pathlib import Path

import numpy as np
import pytest

from svd_jacobi_tpu import SVDConfig
from svd_jacobi_tpu.analysis.concurrency import (inventory, sanitizer,
                                                 static_lint)
from svd_jacobi_tpu.obs import manifest
from svd_jacobi_tpu.resilience import chaos
from svd_jacobi_tpu.serve import ServeConfig, SVDService
from svd_jacobi_tpu.utils import matgen
from svd_jacobi_tpu import config as pkg_config

pytestmark = pytest.mark.conc

FIXDIR = Path(__file__).parent / "fixtures" / "conc_violations"


def _lint(name, order):
    return static_lint.lint_file(FIXDIR / name, rel=name, order=order)


def _codes(findings):
    return dict(Counter(f.code for f in findings))


def _lines(findings):
    return sorted(int(f.where.rsplit(":", 1)[1]) for f in findings)


# ---------------------------------------------------------------------------
# CONC001: lock order, guarded-by, blocking-under-lock, inventory.


class TestLockOrderFixture:
    ORDER = {
        "outer": ("conc001_lock_order.py", "Box._outer", "router"),
        "inner": ("conc001_lock_order.py", "Box._inner", "obs"),
        "peer_a": ("conc001_lock_order.py", "Box._peer_a", "cache"),
        "peer_b": ("conc001_lock_order.py", "Box._peer_b", "cache"),
    }

    def test_every_order_rule_fires(self):
        fs = _lint("conc001_lock_order.py", self.ORDER)
        assert _codes(fs) == {"CONC001": 6}
        by_line = {int(f.where.rsplit(":", 1)[1]): f.message for f in fs}
        assert "inverts the declared order" in by_line[27]   # direct
        assert "no declared order" in by_line[32]            # same rank
        assert "via call" not in by_line[27]
        assert "Box.take_outer" in by_line[41]               # via call
        assert "self-deadlock" in by_line[45]                # Lock re-taken
        assert "no reason" in by_line[56]                    # empty pragma
        assert "inverts the declared order" in by_line[57]   # not excused

    def test_justified_pragma_suppresses(self):
        fs = _lint("conc001_lock_order.py", self.ORDER)
        # The `inverted_but_justified` with-block (line 51) must NOT
        # appear: its pragma carries a reason.
        assert 51 not in _lines(fs)

    def test_forward_order_is_clean(self):
        fs = _lint("conc001_lock_order.py", self.ORDER)
        assert 21 not in _lines(fs) and 22 not in _lines(fs)


class TestGuardedByFixture:
    ORDER = {"counter": ("conc001_guarded_by.py", "Counter._lock",
                         "service")}

    def test_bare_write_flagged_once(self):
        fs = _lint("conc001_guarded_by.py", self.ORDER)
        assert _codes(fs) == {"CONC001": 1}
        (f,) = fs
        assert f.where.endswith(":20")
        assert "locked_bump" in f.message and "racy_reset" in f.message

    def test_init_and_pragma_exempt(self):
        lines = _lines(_lint("conc001_guarded_by.py", self.ORDER))
        assert 12 not in lines and 13 not in lines   # __init__ writes
        assert 27 not in lines                       # pragma'd staging


class TestBlockingFixture:
    ORDER = {"hot": ("conc001_blocking.py", "Hot._lock", "service")}

    def test_blocking_calls_fire(self):
        fs = _lint("conc001_blocking.py", self.ORDER)
        assert _codes(fs) == {"CONC001": 4}
        assert _lines(fs) == [16, 20, 24, 31]
        msgs = " | ".join(f.message for f in fs)
        assert "fsync" in msgs and "result" in msgs
        assert "block_until_ready" in msgs
        assert "Hot._stall_helper" in msgs            # transitive sleep


class TestInventoryFixture:
    def test_undeclared_locks_fire(self):
        fs = _lint("conc001_undeclared.py", {})
        assert _codes(fs) == {"CONC001": 2}
        assert _lines(fs) == [7, 12]
        assert all("no declared tier" in f.message for f in fs)
        # line 14 (`_excused`) is pragma'd with a reason: suppressed.

    def test_stale_declared_row_fires(self):
        fs = _lint("conc001_undeclared.py", {
            "ghost": ("conc001_undeclared.py", "Nope._lock", "obs")})
        stale = [f for f in fs if "stale inventory row" in f.message]
        assert len(stale) == 1 and "ghost" in stale[0].message


class TestCVFixture:
    ORDER = {"cv": ("conc003_cv.py", "Waiter._cond", "queue")}

    def test_cv_rules_fire(self):
        fs = _lint("conc003_cv.py", self.ORDER)
        assert _codes(fs) == {"CONC003": 3, "CONC001": 1}
        by_line = {int(f.where.rsplit(":", 1)[1]): f for f in fs}
        assert "predicate loop" in by_line[23].message
        assert "no timeout" in by_line[28].message
        assert "without holding the owning lock" in by_line[32].message
        # The bare `ready` write is ALSO a guarded-by hit (CONC001).
        assert by_line[31].code == "CONC001"

    def test_conforming_shapes_clean(self):
        lines = _lines(_lint("conc003_cv.py", self.ORDER))
        for ln in (17, 18, 35, 36, 37):   # ok_wait / ok_notify bodies
            assert ln not in lines


# ---------------------------------------------------------------------------
# The real package: clean lint, complete inventory.


class TestRealPackage:
    def test_package_lints_clean(self):
        fs = static_lint.lint_package()
        assert fs == [], "\n".join(f.render() for f in fs)

    def test_inventory_covers_every_lock(self):
        # Two-way: every construction site declared, every declared row
        # alive — with NO pragma escape (the package's own locks must
        # all carry tiers; pragmas are for fixtures and scratch code).
        fs = inventory.check_inventory()
        assert fs == [], "\n".join(f.render() for f in fs)

    def test_declared_tiers_are_ranked(self):
        for name, (rel, qual, tier) in pkg_config.LOCK_ORDER.items():
            assert tier in pkg_config.LOCK_TIER_RANK, (name, tier)

    def test_site_names_resolve_the_serving_locks(self):
        names = set(inventory.site_names().values())
        assert {"service", "fleet", "queue", "journal",
                "router"} <= names


# ---------------------------------------------------------------------------
# CONC002: the runtime sanitizer.


def _import_fixture(name):
    spec = importlib.util.spec_from_file_location(name, FIXDIR / f"{name}.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class TestSanitizer:
    def test_seeded_cycle_detected_with_both_stacks(self):
        fix = _import_fixture("conc002_deadlock")
        with sanitizer.capture() as graph:
            hits = fix.build_cycle()
        assert sorted(hits) == ["ab", "ba"]
        cycle = graph.find_cycle()
        assert cycle is not None and cycle[0] == cycle[-1]
        desc = graph.describe_cycle(cycle)
        assert "->" in desc and "conc002_deadlock.py" in desc
        assert "taken at" in desc and "taken via" in desc
        # Both directions were traversed on distinct named threads.
        assert "conc002-ab" in desc or "conc002-ba" in desc

    def test_acyclic_when_orders_agree(self):
        with sanitizer.capture() as graph:
            # Separate lines: keys are construction sites, and two locks
            # minted on one line would share a key (re-entrant, no edge).
            a = threading.Lock()
            b = threading.Lock()
            for _ in range(3):
                with a:
                    with b:
                        pass
        assert graph.find_cycle() is None
        assert graph.summary()["edge_count"] == 1

    def test_reentrant_rlock_records_no_self_edge(self):
        with sanitizer.capture() as graph:
            r = threading.RLock()
            with r:
                with r:
                    pass
        assert all(src != dst for (src, dst) in graph.edges)
        assert not sanitizer._held()     # balanced on this thread

    def test_condition_wait_keeps_held_set_balanced(self):
        with sanitizer.capture() as graph:
            cond = threading.Condition()
            with cond:
                cond.wait(0.01)          # timeout path
            assert not sanitizer._held()
        assert graph.acquisitions > 0

    def test_zero_cost_when_off(self):
        # Off path: the stdlib factories are THE originals and the
        # sanitizer mutation counter does not move.
        assert threading.Lock is sanitizer._REAL["Lock"]
        assert threading.RLock is sanitizer._REAL["RLock"]
        assert threading.Condition is sanitizer._REAL["Condition"]
        before = sanitizer.mutation_count()
        lk = threading.Lock()
        for _ in range(50):
            with lk:
                pass
        cv = threading.Condition()
        with cv:
            cv.notify_all()
        assert sanitizer.mutation_count() == before

    def test_capture_restores_after_exception(self):
        with pytest.raises(RuntimeError, match="boom"):
            with sanitizer.capture():
                raise RuntimeError("boom")
        assert threading.Lock is sanitizer._REAL["Lock"]

    def test_nested_capture_refused(self):
        with sanitizer.capture():
            with pytest.raises(RuntimeError, match="already active"):
                with sanitizer.capture():
                    pass
        assert threading.Lock is sanitizer._REAL["Lock"]

    def test_lock_names_resolve_to_inventory(self):
        # A lock constructed at a declared package site gets its
        # declared name as its graph key.
        g = sanitizer.LockGraph(inventory.site_names())
        root = inventory.package_root()
        row = pkg_config.LOCK_ORDER["queue"]
        site = next(s for s in inventory.scan_package()
                    if (s.rel, s.qualname) == (row[0], row[1]))
        assert g.key_for(str(root / site.rel), site.lineno) == "queue"


# ---------------------------------------------------------------------------
# Chaos soaks under the instrumented locks.


@pytest.mark.chaos
class TestInstrumentedSoaks:
    def test_kill_lane_soak_acyclic(self):
        """The PR 6 eviction/rescue ladder under CONC002: a 2-lane
        service, one lane killed mid-stream, concurrent clients — every
        ticket terminal OK, and the final acquisition graph (service,
        fleet, queue, journal, breaker, caches, obs...) acyclic."""
        import jax.numpy as jnp
        with sanitizer.capture() as graph:
            cfg = ServeConfig(buckets=((16, 16, "float32"),),
                              solver=SVDConfig(block_size=4),
                              lanes=2, max_queue_depth=32)
            with SVDService(cfg) as svc:
                mats = [matgen.random_dense(12, 12, seed=500 + i,
                                            dtype=jnp.float32)
                        for i in range(8)]
                results = []
                res_lock = threading.Lock()

                def client(chunk):
                    got = [svc.submit(a).result(timeout=600.0)
                           for a in chunk]
                    with res_lock:
                        results.extend(got)

                with chaos.kill_lane(0):
                    ts = [threading.Thread(target=client,
                                           args=(mats[i::2],))
                          for i in range(2)]
                    for t in ts:
                        t.start()
                    for t in ts:
                        t.join()
        assert len(results) == 8
        assert all(r.status.name == "OK" for r in results)
        cycle = graph.find_cycle()
        assert cycle is None, graph.describe_cycle(cycle)
        summary = graph.summary()
        assert summary["edge_count"] > 0
        assert {"service", "queue"} <= set(summary["locks"])

    def test_run_soak_probe_green(self):
        """The `conc` analysis pass's own dynamic probe: no findings,
        acyclic, and the report carries the graph summary."""
        findings, report = sanitizer.run_soak_probe()
        assert findings == [], "\n".join(f.render() for f in findings)
        assert report["cycle"] is None
        assert report["acquisitions"] > 0
        assert report["statuses"] == ["SolveStatus.OK"]


# ---------------------------------------------------------------------------
# Satellite: the per-path append-lock map is LRU-bounded.


class TestAppendLockBound:
    def test_map_is_bounded(self):
        base = len(manifest._APPEND_LOCKS)
        for i in range(manifest._APPEND_LOCKS_MAX * 3):
            manifest._append_lock(f"/tmp/graftlock-bound-{i}")
        assert len(manifest._APPEND_LOCKS) <= manifest._APPEND_LOCKS_MAX
        assert base <= manifest._APPEND_LOCKS_MAX + 1

    def test_held_lock_survives_eviction_pressure(self):
        lk = manifest._append_lock("/tmp/graftlock-held")
        lk.acquire()
        try:
            for i in range(manifest._APPEND_LOCKS_MAX * 3):
                manifest._append_lock(f"/tmp/graftlock-pressure-{i}")
            # Identity preserved while held: a concurrent appender to
            # the same path MUST contend on this same object.
            assert manifest._append_lock("/tmp/graftlock-held") is lk
        finally:
            lk.release()

    def test_append_still_correct_after_eviction(self, tmp_path):
        p = tmp_path / "m.jsonl"
        manifest.append_jsonl(p, {"n": 1}, fsync=False)
        for i in range(manifest._APPEND_LOCKS_MAX * 2):
            manifest._append_lock(f"/tmp/graftlock-churn-{i}")
        manifest.append_jsonl(p, {"n": 2}, fsync=False)   # re-minted lock
        lines = [ln for ln in p.read_text().splitlines() if ln]
        assert len(lines) == 2
