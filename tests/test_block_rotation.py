"""The MXU-native blocked-rotation lane (pair_solver="block_rotation").

Covers the PR's acceptance surface: the accumulated subproblem factor J
is orthogonal to lane tolerance, the lane's sigma/U/V match the existing
pallas lane and the f64 oracle on gap/flat/decaying spectra, a NaN member
still decodes NONFINITE through the batched lane, the serving steppers
and the two-phase sigma/promote flow run the lane end to end, the new
jits keep the once-per-bucket compile contract (RETRACE001), and the
analysis ledger covers the lane (AOT001 bijection + seeded unbudgeted
fixture, zero-collective HLO budget, tune axis/table validity).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import svd_jacobi_tpu as sj
from svd_jacobi_tpu import SVDConfig, solver
from svd_jacobi_tpu.ops import block_rotate, rounds
from svd_jacobi_tpu.resilience import chaos

CFG = SVDConfig(pair_solver="block_rotation", block_size=16)


def _spectrum_matrix(n, spec, seed=7, dtype=jnp.float32):
    rng = np.random.default_rng(seed)
    if spec == "gap":
        sv = np.concatenate([np.ones(4) * 100.0, np.ones(n - 4)])
    elif spec == "flat":
        sv = np.ones(n)
    else:  # decaying
        sv = np.exp(-np.arange(n) / (n / 8))
    qa, _ = np.linalg.qr(rng.standard_normal((n, n)))
    qb, _ = np.linalg.qr(rng.standard_normal((n, n)))
    return jnp.asarray((qa * sv) @ qb.T, dtype)


class TestAccumulate:
    def test_factor_orthogonal_and_diagonalizing(self):
        """J is orthogonal to the f32 Newton-Schulz floor and J^T G J is
        diagonal to the subproblem solve's absolute class."""
        rng = np.random.default_rng(0)
        x = rng.standard_normal((3, 48, 32)).astype(np.float32)
        g = jnp.asarray(np.einsum("kmi,kmj->kij", x, x))
        j = block_rotate.accumulate(g)
        jtj = np.einsum("kij,kil->kjl", np.asarray(j), np.asarray(j))
        eye = np.eye(32)[None]
        assert np.max(np.abs(jtj - eye)) < 5e-6
        rot = np.einsum("kij,kil,klm->kjm", np.asarray(j),
                        np.asarray(g, np.float64), np.asarray(j))
        off = rot - np.eye(32)[None] * np.diagonal(rot, axis1=1, axis2=2)[
            :, None, :] * np.eye(32)[None]
        off = rot * (1.0 - np.eye(32))[None]
        scale = np.max(np.abs(rot))
        assert np.max(np.abs(off)) / scale < 5e-5

    def test_apply_factor_matches_concat_matmul(self):
        rng = np.random.default_rng(1)
        top = jnp.asarray(rng.standard_normal((2, 40, 8)), jnp.float32)
        bot = jnp.asarray(rng.standard_normal((2, 40, 8)), jnp.float32)
        x = rng.standard_normal((2, 24, 16)).astype(np.float32)
        j = block_rotate.accumulate(
            jnp.asarray(np.einsum("kmi,kmj->kij", x, x)))
        nt, nb, _, _ = block_rotate.apply_factor(top, bot, None, None, j)
        ref = np.einsum("kmi,kij->kmj",
                        np.concatenate([top, bot], axis=-1), np.asarray(j))
        got = np.concatenate([np.asarray(nt), np.asarray(nb)], axis=-1)
        np.testing.assert_allclose(got, ref, rtol=0, atol=1e-5)

    def test_abs_panel_stats_segmented(self):
        """The abs-criterion stats segment per member: one member's huge
        coupling never enters a neighbor's statistic."""
        g = np.tile(np.eye(4, dtype=np.float32)[None], (4, 1, 1))
        g[0, 0, 1] = g[0, 1, 0] = 3.0     # member 0's panels: 0, 1
        g = jnp.asarray(g)
        dmax2 = jnp.asarray([1.0, 1.0], jnp.float32)
        stat, skip = rounds.panel_stats(
            g, dmax2, members=rounds._members(2, 2), criterion="abs")
        assert np.asarray(stat).shape == (2,)
        assert float(stat[0]) == pytest.approx(3.0)
        assert float(stat[1]) == pytest.approx(0.0)
        np.testing.assert_array_equal(np.asarray(stat), np.asarray(skip))


class TestLaneAccuracy:
    @pytest.mark.parametrize("spec", ["gap", "flat", "decaying"])
    def test_matches_pallas_and_oracle(self, spec):
        """sigma/U/V of the block lane match the pallas lane and the f64
        oracle on gap/flat/decaying spectra (f32 input, f64 oracle)."""
        n = 96
        a = _spectrum_matrix(n, spec)
        r = sj.svd(a, config=CFG)
        # STAGNATED = the stall detector found the criterion's roundoff
        # floor above the requested tol — a legitimate terminal state on
        # gap spectra (the accuracy asserts below are the contract).
        assert r.status_enum().name in ("OK", "STAGNATED")
        s_ref = np.linalg.svd(np.asarray(a, np.float64), compute_uv=False)
        serr = np.max(np.abs(np.asarray(r.s, np.float64) - s_ref)) / s_ref[0]
        assert serr < 2e-6
        u, s, v = (np.asarray(r.u, np.float64), np.asarray(r.s, np.float64),
                   np.asarray(r.v, np.float64))
        res = np.linalg.norm(np.asarray(a, np.float64) - (u * s) @ v.T)
        assert res / np.linalg.norm(a) < 5e-6
        assert np.max(np.abs(u.T @ u - np.eye(n))) < 5e-5
        assert np.max(np.abs(v.T @ v - np.eye(n))) < 5e-5
        rp = sj.svd(a, config=SVDConfig(pair_solver="pallas", block_size=16))
        np.testing.assert_allclose(np.asarray(r.s), np.asarray(rp.s),
                                   rtol=1e-5, atol=1e-5 * float(s_ref[0]))

    def test_singular_input_contract(self):
        """The reference's numerically singular triangular benchmark
        input: sigma matches the f64 oracle, U (the rotation-product
        side) and the LIVE columns of V are orthonormal. Dead-column V
        directions are noise — the documented caveat the lane shares
        with the abs-class XLA lanes (hybrid/gram-eigh show the same on
        their column-read factor, U), measured by the validator's new
        ``v_orth_live``."""
        from svd_jacobi_tpu.utils import matgen, validation
        a = matgen.random_upper_triangular(128, seed=3)
        r = sj.svd(a, config=CFG)
        s_ref = np.linalg.svd(np.asarray(a, np.float64), compute_uv=False)
        rep = validation.validate(a, r, s_ref=s_ref).as_dict()
        assert rep["sigma_err"] < 2e-6
        assert rep["u_orth"] < 1e-3
        assert rep["v_orth_live"] < 1e-3
        assert rep["residual_rel"] < 1e-4

    def test_wide_input_transposes(self):
        rng = np.random.default_rng(5)
        a = jnp.asarray(rng.standard_normal((64, 96)), jnp.float32)
        r = sj.svd(a, config=CFG)
        s_ref = np.linalg.svd(np.asarray(a, np.float64), compute_uv=False)
        assert np.max(np.abs(np.asarray(r.s, np.float64) - s_ref)) / \
            s_ref[0] < 2e-6
        assert r.u.shape == (64, 64) and r.v.shape == (96, 64)

    def test_batched_matches_oracle_and_isolates_nan_member(self):
        """The batched lane: per-member sigmas match the oracle; a
        chaos-poisoned member decodes NONFINITE with OK neighbors."""
        rng = np.random.default_rng(9)
        stack = jnp.stack([jnp.asarray(rng.standard_normal((64, 64)),
                                       jnp.float32) for _ in range(3)])
        cfg = SVDConfig(pair_solver="block_rotation", block_size=16)
        r = solver.svd_batched(stack, config=cfg)
        for i in range(3):
            assert int(r.status[i]) == int(solver.SolveStatus.OK)
            s_ref = np.linalg.svd(np.asarray(stack[i], np.float64),
                                  compute_uv=False)
            assert np.max(np.abs(np.asarray(r.s[i], np.float64) - s_ref)) \
                / s_ref[0] < 2e-6
        with chaos.nan_at_sweep(1):
            rn = solver.svd_batched(stack, config=cfg)
        assert int(rn.status[0]) == int(solver.SolveStatus.NONFINITE)
        assert int(rn.status[1]) == int(solver.SolveStatus.OK)
        assert int(rn.status[2]) == int(solver.SolveStatus.OK)

    def test_chaos_nan_decodes_nonfinite_fused_and_stepped(self):
        rng = np.random.default_rng(11)
        a = jnp.asarray(rng.standard_normal((96, 96)), jnp.float32)
        with chaos.nan_at_sweep(1):
            r = sj.svd(a, config=CFG)
        assert r.status_enum() is solver.SolveStatus.NONFINITE


class TestSteppers:
    def test_stepper_matches_fused(self):
        rng = np.random.default_rng(13)
        a = jnp.asarray(rng.standard_normal((96, 96)), jnp.float32)
        rf = sj.svd(a, config=CFG)
        st = solver.SweepStepper(a, config=CFG)
        assert st._kernel_path and st.method == "block_rotation"
        assert st.phase_info().stage == "bulk"
        state = st.init()
        while st.should_continue(state):
            state = st.step(state)
        rs = st.finish(state)
        assert rs.status_enum().name == "OK"
        np.testing.assert_allclose(np.asarray(rs.s), np.asarray(rf.s),
                                   rtol=1e-5, atol=1e-4)

    def test_sigma_promote_flow(self):
        """Two-phase serving inherits the lane: sigma_finish defers the
        finish stage and finish_from_payload resumes it exactly."""
        rng = np.random.default_rng(17)
        a = jnp.asarray(rng.standard_normal((96, 64)), jnp.float32)
        st = solver.SweepStepper(a, config=CFG)
        state = st.init()
        while st.should_continue(state):
            state = st.step(state)
        full = st.finish(state)
        sig, payload = st.sigma_finish(state)
        assert payload["promotable"]
        np.testing.assert_allclose(np.asarray(sig.s), np.asarray(full.s),
                                   rtol=1e-4, atol=1e-4)
        promoted = solver.finish_from_payload(payload)
        np.testing.assert_allclose(np.asarray(promoted.s),
                                   np.asarray(full.s), rtol=0, atol=0)

    def test_fused_round_matches_unfused(self):
        """The gram-carried fused block round (eigh + one fused
        apply/exchange/gram kernel, interpret mode here) equals the
        unfused round + a fresh gram of the exchanged stacks."""
        rng = np.random.default_rng(29)
        k, m, b = 4, 96, 8
        top = jnp.asarray(rng.standard_normal((k, m, b)), jnp.float32)
        bot = jnp.asarray(rng.standard_normal((k, m, b)), jnp.float32)
        x = jnp.concatenate([top, bot], axis=-1)
        g = jnp.einsum("kmi,kmj->kij", x, x,
                       precision=jax.lax.Precision.HIGHEST)
        dmax2 = rounds._global_dmax2(top, bot)
        rtol = jnp.float32(1e-5)
        ft, fb, _, _, fg, fstat = rounds.block_round_fused(
            top, bot, None, None, g, dmax2, rtol, interpret=True)
        ut, ub, _, _, ustat = rounds.block_round(
            top, bot, None, None, dmax2, rtol, interpret=True)
        np.testing.assert_allclose(np.asarray(ft), np.asarray(ut),
                                   rtol=0, atol=2e-5)
        np.testing.assert_allclose(np.asarray(fb), np.asarray(ub),
                                   rtol=0, atol=2e-5)
        assert float(fstat) == pytest.approx(float(ustat))
        xg = jnp.concatenate([ut, ub], axis=-1)
        g_ref = jnp.einsum("kmi,kmj->kij", xg, xg,
                           precision=jax.lax.Precision.HIGHEST)
        np.testing.assert_allclose(np.asarray(fg), np.asarray(g_ref),
                                   rtol=0, atol=5e-4)

    def test_mesh_stepper_falls_back_to_pallas(self):
        """The sharded stepper maps block_rotation to the pallas kernel
        lane with SINGLE-stage machinery (the mesh never runs the block
        bulk; without the fallback the bulk/polish stage machine would
        drive abs bookkeeping over rel sharded sweeps)."""
        if len(jax.devices()) < 2:
            pytest.skip("needs a multi-device (virtual CPU) mesh")
        from svd_jacobi_tpu.parallel import sharded
        rng = np.random.default_rng(31)
        a = jnp.asarray(rng.standard_normal((96, 96)), jnp.float32)
        st = sharded.SweepStepper(a, mesh=sharded.make_mesh(),
                                  config=SVDConfig(
                                      pair_solver="block_rotation"))
        assert st.method == "pallas"
        assert st.phase_info().stage == "single"
        state = st.init()
        while st.should_continue(state):
            state = st.step(state)
        r = st.finish(state)
        assert r.status_enum().name == "OK"
        s_ref = np.linalg.svd(np.asarray(a, np.float64), compute_uv=False)
        assert np.max(np.abs(np.asarray(r.s, np.float64) - s_ref)) / \
            s_ref[0] < 2e-6

    def test_aot_entries_cover_both_stages(self):
        rng = np.random.default_rng(19)
        a = jnp.asarray(rng.standard_normal((96, 96)), jnp.float32)
        st = solver.SweepStepper(a, config=CFG)
        names = [n for n, _, _, _ in st.aot_entries()]
        assert "solver._sweep_step_block_jit" in names
        assert "solver._sweep_step_pallas_jit" in names
        stack = jnp.stack([a, a])
        bst = solver.BatchedSweepStepper(stack, config=CFG)
        bnames = [n for n, _, _, _ in bst.aot_entries()]
        assert "solver._sweep_step_block_batched_jit" in bnames
        assert "solver._sweep_step_pallas_batched_jit" in bnames


class TestValidation:
    """criterion="abs" + the block lane routes/raises consistently with
    the pallas guard (the PR's bugfix satellite; cf.
    test_regimes.test_abs_criterion_pallas_validation)."""

    def test_abs_criterion_rejected_like_pallas(self):
        rng = np.random.default_rng(21)
        a = jnp.asarray(rng.standard_normal((96, 96)), jnp.float32)
        with pytest.raises(ValueError, match="criterion='abs'"):
            sj.svd(a, config=SVDConfig(pair_solver="block_rotation",
                                       criterion="abs"))
        with pytest.raises(ValueError, match="criterion='abs'"):
            solver.SweepStepper(a, config=SVDConfig(
                pair_solver="block_rotation", criterion="abs"))
        # auto + abs routes to an abs-capable XLA solver instead: the
        # table may propose either kernel lane, and the capability guard
        # must coerce BOTH away from an unsatisfiable abs request.
        assert solver._resolve_options(
            a, SVDConfig(criterion="abs"), True)[2] == "hybrid"

    def test_pallas_only_modes_rejected(self):
        rng = np.random.default_rng(23)
        a = jnp.asarray(rng.standard_normal((96, 96)), jnp.float32)
        with pytest.raises(ValueError, match="mixed_bulk"):
            sj.svd(a, config=SVDConfig(pair_solver="block_rotation",
                                       mixed_bulk=True))
        with pytest.raises(ValueError, match="double"):
            sj.svd(a, config=SVDConfig(pair_solver="block_rotation",
                                       precondition="double"))

    def test_f64_rejected(self):
        with pytest.raises(ValueError, match="float32"):
            solver._resolve_options(
                jnp.zeros((8, 8), jnp.float64),
                SVDConfig(pair_solver="block_rotation"), True)


class TestAnalysisLedger:
    def test_retrace_once_per_problem(self):
        """Once-per-bucket compiles for the new jits: two shapes, two
        solves each — the repeats must be pure cache hits."""
        from svd_jacobi_tpu.analysis.recompile_guard import RecompileGuard
        rng = np.random.default_rng(27)
        mats = {n: jnp.asarray(rng.standard_normal((n, n)), jnp.float32)
                for n in (48, 64)}
        cfg = SVDConfig(pair_solver="block_rotation", block_size=8,
                        max_sweeps=8)
        with RecompileGuard() as guard:
            guard.expect("solver._svd_block_rotation", problems=2)
            for n, a in mats.items():
                jax.block_until_ready(sj.svd(a, config=cfg).s)
                jax.block_until_ready(sj.svd(a, config=cfg).s)
        assert guard.check() == []
        traces = guard.new_traces()
        assert traces["solver._svd_block_rotation"] == 2

    def test_aot001_bijection_and_seeded_unbudgeted_entry(self):
        """The registry/budget bijection covers the lane, and dropping a
        block-rotation budget fires AOT001 naming the unbudgeted entry
        (the seeded fixture)."""
        from svd_jacobi_tpu import config as _config
        from svd_jacobi_tpu.analysis import aot_checks
        from svd_jacobi_tpu.serve import registry
        assert "solver._svd_block_rotation" in registry.jit_entries()
        assert aot_checks.check_budget_coverage() == []
        budgets = {k: v for k, v in _config.RETRACE_BUDGETS.items()
                   if k != "solver._svd_block_rotation"}
        findings = aot_checks.check_budget_coverage(budgets=budgets)
        assert [f.code for f in findings] == ["AOT001"]
        assert findings[0].where == "solver._svd_block_rotation"

    def test_zero_collective_hlo_budget(self):
        """COLLECTIVE_BUDGET["pallas_block_rotation"]: the lowered fused
        entry carries no collectives of any kind."""
        from svd_jacobi_tpu.analysis import entries, hlo_checks
        probes = {p.name: p
                  for p in entries.single_device_probes(include_f64=False)}
        assert "pallas_block_rotation" in probes
        assert hlo_checks.check_collective_budget(
            probes["pallas_block_rotation"]) == []

    def test_tune_axis_and_table_validity(self):
        """block_rotation is a valid table knob value and rides the
        capability-filtered search axis exactly where the kernel lane
        does (f32, n >= 64)."""
        from svd_jacobi_tpu.tune import search, tables
        t = tables.TuningTable.from_payload({
            "schema_version": tables.SCHEMA_VERSION,
            "table_id": "t", "rows": [
                {"match": {"n_class": "medium"},
                 "knobs": {"pair_solver": "block_rotation"}}],
        }, verify_hash=False)
        assert t.resolve(2048, dtype="float32", backend="cpu",
                         device_kind="cpu").pair_solver == "block_rotation"
        axes = dict(search._axes(512, "float32", {}, smoke=False))
        assert "block_rotation" in axes["pair_solver"]
        axes_f64 = dict(search._axes(512, "float64", {}, smoke=False))
        assert "block_rotation" not in axes_f64["pair_solver"]
        axes_tiny = dict(search._axes(32, "float32", {}, smoke=False))
        assert "block_rotation" not in axes_tiny["pair_solver"]
