"""Network-chaos lane (serve.transport + resilience.netfault): the HTTP
replica transport under an unreliable wire — idempotent retries after
lost ACKs, leases vs partitions, fencing-token split-brain refusal, the
half-open connection breaker, the client-side wall bound, cross-host
journal lock ownership, and the in-process two-"host" drill (drops +
delays + a partition + a replica death with zero lost requests). The
real two-SUBPROCESS partition drill (tests/_http_worker.py) runs in the
chaos+slow lane."""

import json
import os
import signal
import subprocess
import sys
import time
from pathlib import Path

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from svd_jacobi_tpu import SVDConfig  # noqa: E402
from svd_jacobi_tpu.obs import manifest  # noqa: E402
from svd_jacobi_tpu.obs.registry import registry_from_manifest  # noqa: E402
from svd_jacobi_tpu.resilience import chaos  # noqa: E402
from svd_jacobi_tpu.resilience.netfault import FaultyProxy  # noqa: E402
from svd_jacobi_tpu.serve import (AdmissionError, Journal,  # noqa: E402
                                  JournalLockedError, ReplicaRouter,
                                  ReplicaState, RouterConfig, ServeConfig,
                                  StaleFenceError, SVDService,
                                  bump_fence_token, read_fence_token)
from svd_jacobi_tpu.serve.fleet import heartbeat_stale  # noqa: E402
from svd_jacobi_tpu.serve.journal import (_lock_is_remote,  # noqa: E402
                                          host_identity)
from svd_jacobi_tpu.serve.router import ReplicaUnavailable  # noqa: E402
from svd_jacobi_tpu.serve.transport import (WIRE_VERSION,  # noqa: E402
                                            HttpReplica, HttpReplicaServer,
                                            TransportError)
from svd_jacobi_tpu.utils import matgen  # noqa: E402

pytestmark = pytest.mark.net

BUCKETS = ((32, 32, "float64"),)
SOLVER = SVDConfig(block_size=4)


def _serve_cfg(tmp_path, idx=0, **over):
    base = dict(buckets=BUCKETS, solver=SOLVER, max_queue_depth=32,
                brownout_sigma_only_at=2.0, brownout_shed_at=2.0,
                result_cache_bytes=16 << 20, compute_digest=True,
                journal_path=str(tmp_path / f"journal-{idx}.jsonl"))
    base.update(over)
    return ServeConfig(**base)


def _mat(m, n, seed):
    return np.asarray(matgen.random_dense(m, n, seed=seed,
                                          dtype=jnp.float64))


def _sref(a):
    return np.linalg.svd(np.asarray(a, np.float64), compute_uv=False)


def _wait(pred, timeout=60.0, interval=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return False


def _journal_counts(path, ids):
    """Per-id admit/finalize counts from the RAW journal stream (what a
    postmortem would read — not the in-memory bookkeeping)."""
    admits, finals = {}, {}
    recs, _ = manifest.read_jsonl_tolerant(path, quarantine=False)
    for r in recs:
        rid = r.get("id")
        if rid not in ids:
            continue
        if r.get("kind") == "admit":
            admits[rid] = admits.get(rid, 0) + 1
        elif r.get("kind") == "finalize":
            finals[rid] = finals.get(rid, 0) + 1
    return admits, finals


def _audits(path, kind):
    """Audit records (journal.append_audit) ride the journal stream
    with their kind as the record kind."""
    recs, _ = manifest.read_jsonl_tolerant(path, quarantine=False)
    return [r for r in recs if r.get("kind") == kind]


def _poll_result(replica, sub, timeout=120.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        res = sub.poll(0.05)
        if res is not None:
            return res
    raise TimeoutError(f"no result for {sub.request_id}")


# ---------------------------------------------------------------------------
# Satellite: cross-host journal lock ownership.


class TestCrossHostLock:
    def _remote_lock(self, journal):
        lock = Path(str(journal) + ".lock")
        lock.write_text(json.dumps({
            "pid": 12345, "boot_id": "another-boot",
            "host": "some-other-machine", "token": "deadbeef",
            "t_wall": time.time(), "path": str(journal)}))
        return lock

    def test_lock_is_remote_unit(self):
        assert _lock_is_remote({"host": "some-other-machine"})
        assert not _lock_is_remote({"host": host_identity()})
        # Pre-host-field lockfiles (older writers) keep the same-host
        # treatment — remoteness must be PROVEN, not assumed.
        assert not _lock_is_remote({})
        assert not _lock_is_remote({"host": 7})

    def test_remote_lock_refused_on_open(self, tmp_path):
        """A lock minted on another machine can never be auto-broken:
        its pid/boot-id liveness means nothing here."""
        journal = tmp_path / "j.jsonl"
        self._remote_lock(journal)
        with pytest.raises(JournalLockedError) as ei:
            Journal(journal, exclusive=True)
        msg = str(ei.value)
        assert "some-other-machine" in msg
        assert "force=True" in msg

    def test_break_lock_refuses_remote_without_force(self, tmp_path):
        journal = tmp_path / "j.jsonl"
        lock = self._remote_lock(journal)
        with pytest.raises(JournalLockedError) as ei:
            Journal.break_lock(journal)
        assert "fence" in str(ei.value).lower()
        assert lock.exists()
        # force=True is the FENCED cross-machine rescue path.
        assert Journal.break_lock(journal, force=True)
        assert not lock.exists()

    def test_same_host_live_owner_still_refused(self, tmp_path):
        """The cross-host refusal must not regress the same-host rule:
        a second live opener on THIS host still fails loudly."""
        journal = tmp_path / "j.jsonl"
        j = Journal(journal, exclusive=True)
        try:
            with pytest.raises(JournalLockedError) as ei:
                Journal(journal, exclusive=True)
            assert "LIVE process" in str(ei.value)
            # And same-host break_lock (no force) still works — the
            # supervisor's declared-dead override is a local decision.
            assert Journal.break_lock(journal)
        finally:
            j.release()


# ---------------------------------------------------------------------------
# The wire protocol on a clean network.


class TestWireProtocol:
    def test_submit_solve_result_roundtrip(self, tmp_path):
        server = HttpReplicaServer(_serve_cfg(tmp_path)).start()
        try:
            rep = HttpReplica(0, server.address,
                              tmp_path / "journal-0.jsonl")
            a = _mat(30, 24, seed=1)
            sub = rep.submit(a, deadline_s=300.0, request_id="wire-0")
            res = _poll_result(rep, sub)
            assert res.error is None and res.status.name == "OK"
            assert np.abs(np.asarray(res.s, np.float64)
                          - _sref(a)).max() < 1e-8
            # Wide input: orientation is client-side, factors swap back.
            b = _mat(24, 30, seed=2)
            res2 = _poll_result(rep, rep.submit(
                b, deadline_s=300.0, request_id="wire-1"))
            assert res2.status.name == "OK"
            assert np.abs(np.asarray(res2.s, np.float64)
                          - _sref(b)).max() < 1e-8
            assert res2.u.shape[0] == 24 and res2.v.shape[0] == 30
            # forget: the consumed result is released server-side.
            sub.cleanup()
            assert not rep._rpc("status", "/v1/status?id=wire-0",
                                method="GET", attempts=1)["done"]
        finally:
            server.stop(drain=True, timeout=30.0)

    def test_healthz_shape(self, tmp_path):
        server = HttpReplicaServer(_serve_cfg(tmp_path)).start()
        try:
            rep = HttpReplica(0, server.address,
                              tmp_path / "journal-0.jsonl")
            hz = rep._refresh(force=True)
            assert hz["ok"] and not hz["fenced"]
            assert hz["wire_version"] == WIRE_VERSION
            assert hz["pid"] == os.getpid()
            assert hz["fence_token"] == 0
            assert hz["host"] == host_identity()
            assert rep.alive()
            # The first contact granted a lease via the formal RPC.
            assert rep.net_stats.get("lease_grant") == 1
            assert rep.lease_until(time.monotonic()) is not None
        finally:
            server.stop(drain=True, timeout=30.0)

    def test_wire_version_mismatch_refused(self, tmp_path):
        server = HttpReplicaServer(_serve_cfg(tmp_path)).start()
        try:
            rep = HttpReplica(0, server.address,
                              tmp_path / "journal-0.jsonl")
            resp = rep._rpc("submit", "/v1/submit", body={
                "kind": "submit", "wire_version": WIRE_VERSION + 1,
                "id": "vX", "t_wall": time.time(), "input": None})
            assert not resp["ok"]
            assert "wire version" in resp["error"]
        finally:
            server.stop(drain=True, timeout=30.0)

    def test_unknown_path_is_an_answer_not_an_error(self, tmp_path):
        """HTTP-level errors mean TRANSPORT failure only; an unknown
        path still answers 200 + ok=false."""
        server = HttpReplicaServer(_serve_cfg(tmp_path)).start()
        try:
            rep = HttpReplica(0, server.address,
                              tmp_path / "journal-0.jsonl")
            resp = rep._rpc("nope", "/v1/nope", body={}, attempts=1)
            assert resp == {"ok": False, "error": "unknown path /v1/nope"}
        finally:
            server.stop(drain=True, timeout=30.0)

    def test_rejection_maps_to_admission_error(self, tmp_path):
        server = HttpReplicaServer(
            _serve_cfg(tmp_path, max_queue_depth=32)).start()
        try:
            rep = HttpReplica(0, server.address,
                              tmp_path / "journal-0.jsonl")
            with pytest.raises(AdmissionError) as ei:
                # 64x64 routes to no declared bucket: the SERVER's
                # admission verdict crosses the wire as a typed reason,
                # not a transport failure.
                rep.submit(np.zeros((64, 64)), deadline_s=30.0,
                           request_id="bad-shape")
            assert ei.value.reason.name == "NO_BUCKET"
        finally:
            server.stop(drain=True, timeout=30.0)


# ---------------------------------------------------------------------------
# Idempotency under duplication / lost ACKs (the fault proxy on the wire).


class TestIdempotency:
    def test_duplicated_submit_admits_once(self, tmp_path):
        journal = tmp_path / "journal-0.jsonl"
        server = HttpReplicaServer(_serve_cfg(tmp_path)).start()
        try:
            with FaultyProxy(server.address) as proxy:
                proxy.arm("duplicate", shots=1)
                rep = HttpReplica(0, proxy.address, journal)
                a = _mat(28, 20, seed=3)
                sub = rep.submit(a, deadline_s=300.0,
                                 request_id="dup-0")
                res = _poll_result(rep, sub)
                assert res.status.name == "OK"
                assert proxy.unconsumed() == {}
            admits, finals = _journal_counts(journal, {"dup-0"})
            assert admits == {"dup-0": 1}
            assert finals == {"dup-0": 1}
        finally:
            server.stop(drain=True, timeout=30.0)

    def test_lost_ack_retry_admits_once(self, tmp_path):
        """The tentpole's core scenario: the submit is DELIVERED but
        its ACK is blackholed — the client must retry (it cannot know),
        and the retry must be exactly-once."""
        journal = tmp_path / "journal-0.jsonl"
        server = HttpReplicaServer(_serve_cfg(tmp_path)).start()
        try:
            with FaultyProxy(server.address) as proxy:
                proxy.arm("blackhole_reply", shots=1)
                rep = HttpReplica(0, proxy.address, journal,
                                  rpc_timeout_s=0.5)
                a = _mat(28, 20, seed=4)
                sub = rep.submit(a, deadline_s=300.0,
                                 request_id="ack-0")
                res = _poll_result(rep, sub)
                assert res.status.name == "OK"
                # The retry really happened (attempt 1's ACK was eaten).
                assert rep.net_stats.get("rpc_retry", 0) >= 1
                assert proxy.unconsumed() == {}
            admits, finals = _journal_counts(journal, {"ack-0"})
            assert admits == {"ack-0": 1}
            assert finals == {"ack-0": 1}
        finally:
            server.stop(drain=True, timeout=30.0)

    def test_dropped_submit_is_retried_transparently(self, tmp_path):
        server = HttpReplicaServer(_serve_cfg(tmp_path)).start()
        try:
            with FaultyProxy(server.address) as proxy:
                proxy.arm("drop", shots=1)
                proxy.arm("delay", shots=1, value=0.1)
                rep = HttpReplica(0, proxy.address,
                                  tmp_path / "journal-0.jsonl",
                                  rpc_timeout_s=0.5)
                res = _poll_result(rep, rep.submit(
                    _mat(26, 20, seed=5), deadline_s=300.0,
                    request_id="drop-0"))
                assert res.status.name == "OK"
                assert rep.net_stats.get("rpc_retry", 0) >= 1
                assert proxy.unconsumed() == {}
        finally:
            server.stop(drain=True, timeout=30.0)

    def test_duplicated_debt_admits_once(self, tmp_path):
        """Partition-during-rescue flap: the debt hand-off is delivered
        TWICE (a proxy retransmit) — the receiver's rid dedupe + the
        service's fence ledger keep it exactly-once."""
        journal = tmp_path / "journal-0.jsonl"
        server = HttpReplicaServer(_serve_cfg(tmp_path)).start()
        try:
            with FaultyProxy(server.address) as proxy:
                proxy.arm("duplicate", shots=1)
                rep = HttpReplica(0, proxy.address, journal)
                rec = {
                    "kind": "admit", "id": "debt-0",
                    "t_wall": time.time(), "attempt": 1,
                    "deadline_s": 300.0, "m": 32, "n": 32,
                    "orig_shape": [28, 20], "transposed": False,
                    "bucket": "32x32:float64",
                    "compute_u": True, "compute_v": True,
                    "degraded": False, "brownout": "FULL",
                    "top_k": None, "phase": "full",
                    "input": __import__(
                        "svd_jacobi_tpu.serve.journal",
                        fromlist=["_encode_array"])._encode_array(
                            _mat(28, 20, seed=6)),
                }
                subs = rep.admit_debt(
                    [rec], fence_token=1,
                    fence_domain=str(tmp_path / "dead.jsonl"))
                res = _poll_result(rep, subs["debt-0"])
                assert res.status.name == "OK"
                assert proxy.unconsumed() == {}
            admits, finals = _journal_counts(journal, {"debt-0"})
            assert admits == {"debt-0": 1}
            assert finals == {"debt-0": 1}
        finally:
            server.stop(drain=True, timeout=30.0)


# ---------------------------------------------------------------------------
# Leases, fencing, split-brain.


class TestLeaseAndFencing:
    def test_lease_survives_short_partition(self, tmp_path):
        """An unexpired lease is a liveness promise: a partition
        SHORTER than the TTL must not declare the replica dead."""
        server = HttpReplicaServer(_serve_cfg(tmp_path)).start()
        try:
            with FaultyProxy(server.address) as proxy:
                rep = HttpReplica(0, proxy.address,
                                  tmp_path / "journal-0.jsonl",
                                  lease_ttl_s=2.0, rpc_timeout_s=0.3,
                                  hz_interval_s=0.05)
                assert rep.alive()
                proxy.partition()
                assert rep.alive()        # lease still holds
                proxy.heal()
                assert _wait(lambda: rep.alive(), timeout=5.0)
        finally:
            server.stop(drain=True, timeout=30.0)

    def test_lease_expiry_then_partition_heal(self, tmp_path):
        server = HttpReplicaServer(_serve_cfg(tmp_path)).start()
        try:
            with FaultyProxy(server.address) as proxy:
                rep = HttpReplica(0, proxy.address,
                                  tmp_path / "journal-0.jsonl",
                                  lease_ttl_s=0.3, rpc_timeout_s=0.2,
                                  hz_interval_s=0.02)
                assert rep.alive()
                proxy.partition()
                assert _wait(lambda: not rep.alive(), timeout=5.0)
                assert rep.death_cause() == "lease_expired"
                assert rep.net_stats.get("lease_expired") == 1
                proxy.heal()
                assert _wait(lambda: rep.alive(), timeout=5.0)
                # The re-grant is a formal reconciliation event.
                assert rep.net_stats.get("partition_heal") == 1
                assert rep.net_stats.get("lease_grant", 0) >= 2
        finally:
            server.stop(drain=True, timeout=30.0)

    def test_self_fence_on_disk_token(self, tmp_path):
        """A partitioned-but-ALIVE replica observes a newer fence token
        on the shared filesystem and stops serving — it can never
        double-serve debt a rescuer claimed."""
        journal = tmp_path / "journal-0.jsonl"
        server = HttpReplicaServer(_serve_cfg(tmp_path)).start()
        try:
            rep = HttpReplica(0, server.address, journal)
            assert rep.alive()
            token = bump_fence_token(journal, minted_by="test-rescuer")
            assert token == 1

            def fenced():
                hz = rep._refresh(force=True)
                return bool(hz.get("fenced"))
            assert _wait(fenced, timeout=5.0)
            assert not rep.alive()
            assert rep.death_cause() == "replica_fenced"
            # Fenced refusals on the write paths.
            resp = rep._rpc("submit", "/v1/submit", body={
                "kind": "submit", "wire_version": WIRE_VERSION,
                "id": "post-fence", "t_wall": time.time(),
                "input": None})
            assert resp == {"ok": False, "fenced": True}
            with pytest.raises(ReplicaUnavailable):
                rep.admit_debt([], fence_token=None, fence_domain=None)
            # The self-fence is journal-audited.
            audits = _audits(journal, "self_fence")
            assert len(audits) == 1 and audits[0]["token"] == 1
        finally:
            server.stop(drain=True, timeout=30.0)

    def test_stale_fence_refused_split_brain(self, tmp_path):
        """Two rescuers race over the same dead domain: the NEWER token
        wins, the older one is refused loudly + audited, an EQUAL
        token's duplicate rids are skipped as idempotent replays."""
        journal = tmp_path / "journal-0.jsonl"
        server = HttpReplicaServer(_serve_cfg(tmp_path)).start()
        try:
            rep = HttpReplica(0, server.address, journal)
            domain = str(tmp_path / "dead.jsonl")
            from svd_jacobi_tpu.serve.journal import _encode_array

            def debt(rid, seed):
                return {"kind": "admit", "id": rid,
                        "t_wall": time.time(), "attempt": 1,
                        "deadline_s": 300.0, "m": 32, "n": 32,
                        "orig_shape": [28, 20], "transposed": False,
                        "bucket": "32x32:float64", "compute_u": True,
                        "compute_v": True, "degraded": False,
                        "brownout": "FULL", "top_k": None,
                        "phase": "full",
                        "input": _encode_array(_mat(28, 20, seed=seed))}

            subs = rep.admit_debt([debt("race-0", 7)], fence_token=2,
                                  fence_domain=domain)
            assert _poll_result(rep, subs["race-0"]).status.name == "OK"
            # The LOSING rescuer (older token) hears the refusal.
            with pytest.raises(StaleFenceError):
                rep.admit_debt([debt("race-1", 8)], fence_token=1,
                               fence_domain=domain)
            refusals = _audits(journal, "fence_refused")
            assert len(refusals) == 1
            assert refusals[0]["token"] == 1
            assert refusals[0]["held_token"] == 2
            # An EQUAL token replaying the same rid is idempotent.
            subs2 = rep.admit_debt([debt("race-0", 7)], fence_token=2,
                                   fence_domain=domain)
            assert set(subs2) == {"race-0"}   # a poll surface, no re-admit
            admits, _ = _journal_counts(journal, {"race-0", "race-1"})
            assert admits == {"race-0": 1}
            assert _audits(journal, "fence_dup_skipped")
        finally:
            server.stop(drain=True, timeout=30.0)

    def test_fence_rpc_older_than_boot_ignored(self, tmp_path):
        """A respawned replica must not re-die on a fence aimed at its
        PREVIOUS life."""
        server = HttpReplicaServer(_serve_cfg(tmp_path)).start()
        try:
            rep = HttpReplica(0, server.address,
                              tmp_path / "journal-0.jsonl")
            resp = rep._rpc("fence", "/v1/fence", body={
                "t_wall": server.boot_wall - 10.0})
            assert resp == {"ok": True, "ignored": True}
            assert rep._refresh(force=True)["ok"]
        finally:
            server.stop(drain=True, timeout=30.0)

    def test_heartbeat_stale_lease_unit(self):
        # An unexpired lease vetoes staleness outright.
        assert not heartbeat_stale(
            10.0, 0.0, busy=False, holds_work=True, idle_timeout_s=1.0,
            busy_timeout_s=5.0, lease_until=11.0)
        # Expired lease: the ordinary two-tier verdict resumes.
        assert heartbeat_stale(
            10.0, 0.0, busy=False, holds_work=True, idle_timeout_s=1.0,
            busy_timeout_s=5.0, lease_until=9.0)
        assert not heartbeat_stale(
            10.0, 0.0, busy=False, holds_work=False, idle_timeout_s=1.0,
            busy_timeout_s=5.0, lease_until=9.0)


# ---------------------------------------------------------------------------
# The half-open connection breaker.


class TestConnectionBreaker:
    def test_quarantine_opens_and_heals(self, tmp_path):
        server = HttpReplicaServer(_serve_cfg(tmp_path)).start()
        try:
            live = server.address
            rep = HttpReplica(0, ("127.0.0.1", 1),   # nothing listens
                              tmp_path / "journal-0.jsonl",
                              rpc_attempts=1, rpc_timeout_s=0.2,
                              quarantine_threshold=2,
                              quarantine_cooldown_s=0.2)
            a = _mat(26, 20, seed=9)
            for _ in range(2):
                with pytest.raises(ReplicaUnavailable):
                    rep.submit(a, deadline_s=30.0, request_id="q-0")
            assert rep.net_stats.get("quarantine") == 1
            # Open breaker: the next submit fails with ZERO network I/O
            # (instant ring failover), not another timeout.
            t0 = time.monotonic()
            with pytest.raises(ReplicaUnavailable) as ei:
                rep.submit(a, deadline_s=30.0, request_id="q-1")
            assert time.monotonic() - t0 < 0.1
            assert "quarantined" in str(ei.value)
            # Cooldown passes, the address heals -> half-open probe
            # closes the breaker.
            rep.address = live
            time.sleep(0.25)
            res = _poll_result(rep, rep.submit(
                a, deadline_s=300.0, request_id="q-2"))
            assert res.status.name == "OK"
            assert rep.net_stats.get("heal", 0) >= 1
        finally:
            server.stop(drain=True, timeout=30.0)


# ---------------------------------------------------------------------------
# Satellite: the client-side wall bound (a blackholed replica cannot
# hang the router's client).


class TestClientWallBound:
    def test_blackholed_replica_resolves_client_deadline(self, tmp_path):
        server = HttpReplicaServer(_serve_cfg(tmp_path)).start()
        proxy = FaultyProxy(server.address).start()
        try:
            rep = HttpReplica(0, proxy.address,
                              tmp_path / "journal-0.jsonl",
                              lease_ttl_s=600.0, rpc_timeout_s=0.3)
            cfg = RouterConfig(
                replicas=1, serve=_serve_cfg(tmp_path, idx=9),
                state_dir=str(tmp_path / "router-state"),
                supervise_interval_s=0.05, heartbeat_timeout_s=600.0,
                client_grace_s=0.5)
            with chaos.slow_solve(1.0, shots=8):
                router = ReplicaRouter(cfg, replicas=[rep]).start()
                try:
                    t = router.submit(_mat(28, 20, seed=10),
                                      deadline_s=1.0)
                    # The replica answers the submit, then vanishes.
                    proxy.partition()
                    t0 = time.monotonic()
                    res = t.result(timeout=60.0)
                    took = time.monotonic() - t0
                finally:
                    router.stop(drain=False, timeout=5.0)
            # deadline (1.0s) + grace (0.5s), not the 60s client
            # timeout and not forever.
            assert res.status is not None
            assert res.status.name == "DEADLINE"
            assert res.path == "client_deadline"
            assert res.degraded
            assert took < 20.0
        finally:
            proxy.stop()
            server.stop(drain=False, timeout=5.0)


# ---------------------------------------------------------------------------
# Net observability: records -> offline metric reconstruction.


class TestNetObservability:
    def test_registry_reconstruction(self, tmp_path):
        mpath = tmp_path / "manifest.jsonl"
        server = HttpReplicaServer(_serve_cfg(tmp_path)).start()
        try:
            with FaultyProxy(server.address) as proxy:
                rep = HttpReplica(0, proxy.address,
                                  tmp_path / "journal-0.jsonl",
                                  rpc_timeout_s=0.3,
                                  manifest_path=str(mpath))
                assert rep.healthz() is not None   # grants the lease
                proxy.arm("drop", shots=1)
                res = _poll_result(rep, rep.submit(
                    _mat(26, 20, seed=11), deadline_s=300.0,
                    request_id="obs-0"))
                assert res.status.name == "OK"
        finally:
            server.stop(drain=True, timeout=30.0)
        recs, torn = manifest.read_jsonl_tolerant(mpath, quarantine=False)
        assert torn == 0
        assert all(r.get("kind") == "net" for r in recs)
        reg = registry_from_manifest(recs)
        assert reg.value("svdj_rpc_retries_total", op="submit",
                         replica="0") >= 1
        assert reg.value("svdj_replica_leases_total", replica="0",
                         event="lease_grant") >= 1
        # The live counters agree with the offline reconstruction.
        assert rep.net_stats["rpc_retry"] == reg.value(
            "svdj_rpc_retries_total", op="submit", replica="0")


# ---------------------------------------------------------------------------
# The in-process two-"host" drill: drops + delays + a partition + a
# replica death, closed loop, zero lost requests, exactly-once across
# the federation, fencing audited.


class TestTwoHostDrill:
    def test_chaos_drill_zero_lost_exactly_once(self, tmp_path):
        cache = tmp_path / "shared-cache"
        servers, proxies = [], []
        journals = [tmp_path / f"journal-{i}.jsonl" for i in (0, 1)]
        try:
            for i in (0, 1):
                cfg = _serve_cfg(tmp_path, idx=i,
                                 compile_cache_dir=str(cache))
                servers.append(HttpReplicaServer(cfg, warmup=True).start())
                proxy = FaultyProxy(servers[i].address).start()
                proxy.arm("drop", shots=1)
                proxy.arm("delay", shots=1, value=0.1)
                proxies.append(proxy)
            # Replica 1 (the survivor-to-be) warm-booted from the cache
            # namespace replica 0 populated.
            assert servers[1].coldstart is not None
            assert servers[1].coldstart["fresh_compiles"] == 0

            handles = [
                HttpReplica(i, proxies[i].address, journals[i],
                            lease_ttl_s=1.0, rpc_timeout_s=0.5,
                            hz_interval_s=0.05, boot_grace_s=5.0)
                for i in (0, 1)]
            cfg = RouterConfig(
                replicas=2, serve=_serve_cfg(tmp_path, idx=8),
                state_dir=str(tmp_path / "router-state"),
                supervise_interval_s=0.05, heartbeat_timeout_s=2.0,
                probe_interval_s=0.5, probe_timeout_s=180.0)
            router = ReplicaRouter(cfg, replicas=handles).start()
            try:
                rng = np.random.default_rng(0)
                mats = [rng.standard_normal((28, 20)) for _ in range(8)]
                from svd_jacobi_tpu.serve import input_digest
                victim = router.ring.owner("32x32:float64",
                                           input_digest(mats[0]))
                survivor = 1 - victim
                # The first dispatches are pinned slow (process-global
                # shots), so the kill below lands while the victim is
                # mid-solve — it dies holding journaled-but-unfinalized
                # debt, never a finalized-but-unfetched result (which
                # would be a LOUD lost-result error, a different
                # drill).
                with chaos.slow_solve(1.5, shots=4):
                    tickets = [router.submit(m, deadline_s=600.0,
                                             request_id=f"net-{i:02d}")
                               for i, m in enumerate(mats)]
                    # One short partition on the SURVIVOR: shorter than
                    # its lease TTL, so the lease absorbs it (no
                    # eviction) and the wire chaos rides on top.
                    proxies[survivor].flap(0.4)
                    # Kill the victim once it holds journaled-but-
                    # UNFINALIZED debt: the rescue must re-home it.
                    assert _wait(
                        lambda: bool(Journal(journals[victim]).scan(
                            quarantine=False).unfinalized),
                        timeout=120.0)
                    servers[victim].simulate_kill()
                    results = [t.result(timeout=600.0) for t in tickets]
                # Zero lost requests; every result matches the oracle.
                for m, res in zip(mats, results):
                    assert res.error is None, res
                    assert res.status.name == "OK"
                    assert np.abs(np.asarray(res.s, np.float64)
                                  - _sref(m)).max() < 1e-6
                assert router.total_rescues >= 1
                # The rescue was FENCED: token minted before the lock
                # broke, recorded in the router's rescue record.
                assert read_fence_token(journals[victim]) >= 1
                rescues = [r for r in router.records()
                           if r.get("event") == "rescue"]
                assert rescues and rescues[-1].get("fence_token", 0) >= 1
                # Exactly-once across the federation, from the RAW
                # journal streams (merged postmortem view).
                ids = {t.request_id for t in tickets}
                finals_all = {}
                for jp in journals:
                    _, finals = _journal_counts(jp, ids)
                    assert all(c == 1 for c in finals.values()), finals
                    for rid in finals:
                        finals_all[rid] = finals_all.get(rid, 0) + 1
                assert set(finals_all) == ids
                assert all(c == 1 for c in finals_all.values()), finals_all
                # All armed chaos actually fired.
                for proxy in proxies:
                    assert proxy.unconsumed() == {}
                # The wire discipline was exercised, not bypassed.
                stats = {}
                for h in handles:
                    for k, v in h.net_stats.items():
                        stats[k] = stats.get(k, 0) + v
                assert stats.get("rpc_retry", 0) >= 1
                assert stats.get("lease_grant", 0) >= 2
            finally:
                router.stop(drain=False, timeout=10.0)
        finally:
            for proxy in proxies:
                proxy.stop()
            for server in servers:
                server.stop(drain=False, timeout=10.0)


# ---------------------------------------------------------------------------
# The real two-SUBPROCESS partition drill (chaos + slow): a LIVE but
# partitioned worker process is rescued away, self-fences through the
# shared filesystem (exit code 5), and its warm respawn pays zero fresh
# compiles.


def _spawn_http_worker(tmp_path, idx, cache, warmup=True, slow_s=0.0):
    journal = tmp_path / f"journal-{idx}.jsonl"
    announce = tmp_path / f"announce-{idx}.json"
    announce.unlink(missing_ok=True)
    argv = [sys.executable,
            str(Path(__file__).resolve().parent / "_http_worker.py"),
            "serve", "--journal", str(journal),
            "--announce", str(announce),
            "--cache", str(cache), "--replica", str(idx),
            "--max-runtime-s", "900"]
    if warmup:
        argv.append("--warmup")
    if slow_s > 0:
        argv += ["--slow-s", str(slow_s)]
    log = open(tmp_path / f"worker-{idx}.log", "a")
    proc = subprocess.Popen(argv, stdout=log, stderr=log)
    return proc, journal, announce


def _wait_announce(announce, timeout=240.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if announce.exists():
            try:
                return json.loads(announce.read_text())
            except json.JSONDecodeError:
                pass
        time.sleep(0.1)
    raise TimeoutError(f"no announce at {announce}")


@pytest.mark.chaos
@pytest.mark.slow
class TestSubprocessPartitionDrill:
    def test_partitioned_worker_rescued_fenced_respawned(self, tmp_path):
        cache = tmp_path / "shared-cache"
        procs, proxies = {}, {}
        try:
            # Worker 0 solves SLOWLY (5s/sweep): the partition below
            # always lands while its debt is journaled-but-unfinalized,
            # so the fenced rescue re-homes it — and when the zombie's
            # solve finally completes, the stale-finalize gate (disk
            # fence token) refuses the duplicate. No warmup: the slow
            # hook would crawl through it too, and worker 0's cold
            # first dispatch only widens the window. Worker 1 stays
            # fast and warms the shared cache.
            p0, journal0, announce0 = _spawn_http_worker(
                tmp_path, 0, cache, warmup=False, slow_s=5.0)
            ann0 = _wait_announce(announce0)
            procs[0] = p0
            p1, journal1, announce1 = _spawn_http_worker(
                tmp_path, 1, cache, slow_s=0.10)
            ann1 = _wait_announce(announce1)
            procs[1] = p1
            # Worker 0 sits behind the fault proxy so the drill can
            # PARTITION it (alive, reachable disk, unreachable wire).
            proxies[0] = FaultyProxy(
                (ann0["host"], ann0["port"])).start()

            replicas = [
                HttpReplica(0, proxies[0].address, journal0,
                            lease_ttl_s=1.0, rpc_timeout_s=0.5,
                            hz_interval_s=0.05),
                HttpReplica(1, (ann1["host"], ann1["port"]), journal1,
                            lease_ttl_s=1.0, rpc_timeout_s=0.5,
                            hz_interval_s=0.05),
            ]
            cfg = RouterConfig(
                replicas=2,
                serve=ServeConfig(
                    buckets=((48, 32, "float32"),),
                    solver=SVDConfig(pair_solver="pallas"),
                    max_queue_depth=64,
                    brownout_sigma_only_at=2.0, brownout_shed_at=2.0),
                state_dir=str(tmp_path),
                supervise_interval_s=0.05,
                heartbeat_timeout_s=2.0,
                probe_interval_s=0.5, probe_timeout_s=180.0)
            router = ReplicaRouter(cfg, replicas=replicas).start()
            try:
                rng = np.random.default_rng(0)
                mats = [rng.standard_normal((40, 30)).astype(np.float32)
                        for _ in range(8)]
                tickets = [router.submit(m, deadline_s=600.0,
                                         request_id=f"part-{i:02d}")
                           for i, m in enumerate(mats)]
                # Partition worker 0 once it holds journaled-but-
                # unfinalized debt. The process stays ALIVE — only the
                # wire goes dark.
                assert _wait(lambda: bool(Journal(journal0).scan(
                    quarantine=False).unfinalized), timeout=120.0)
                proxies[0].partition()

                results = [t.result(timeout=600.0) for t in tickets]
                for m, res in zip(mats, results):
                    assert res.error is None, res
                    assert res.status.name == "OK"
                    sref = np.linalg.svd(np.asarray(m, np.float64),
                                         compute_uv=False)
                    assert np.abs(np.asarray(res.s, np.float64)
                                  - sref).max() < 5e-4
                assert router.total_rescues >= 1

                # The partitioned-but-alive worker self-fences through
                # the shared filesystem and EXITS with the fence code —
                # it never double-serves the rescued debt.
                assert read_fence_token(journal0) >= 1
                assert procs[0].wait(timeout=120.0) == 5
                assert _audits(journal0, "self_fence")

                # Exactly-once across both journals.
                ids = {t.request_id for t in tickets}
                finals_all = {}
                for jp in (journal0, journal1):
                    _, finals = _journal_counts(jp, ids)
                    assert all(c == 1 for c in finals.values()), finals
                    for rid in finals:
                        finals_all[rid] = finals_all.get(rid, 0) + 1
                assert set(finals_all) == ids
                assert all(c == 1 for c in finals_all.values())

                # Respawn: a fresh process on the SAME journal (the
                # fence token on disk is now its acknowledged baseline)
                # — reachable directly, warm from the shared cache.
                proxies[0].heal()

                def respawn():
                    p, _, ann = _spawn_http_worker(tmp_path, 0, cache,
                                                   warmup=True)
                    procs[0] = p
                    a = _wait_announce(ann)
                    return (a["host"], a["port"])
                replicas[0]._respawn_cmd = respawn
                assert _wait(lambda: replicas[0].state
                             is ReplicaState.ACTIVE, timeout=240.0)
                hz = replicas[0]._refresh(force=True)
                assert hz["ok"] and not hz["fenced"]
                assert hz["pid"] == procs[0].pid
                # Warm respawn: zero fresh compiles off the shared
                # persistent cache namespace.
                assert hz["coldstart"] is not None
                assert hz["coldstart"]["fresh_compiles"] == 0
                assert hz["coldstart"]["cache_hits"] > 0
            finally:
                router.stop(drain=True, timeout=60.0)
        finally:
            for proxy in proxies.values():
                proxy.stop()
            for p in procs.values():
                if p.poll() is None:
                    p.kill()
                    p.wait(timeout=30)
