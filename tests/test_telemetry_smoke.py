"""Tier-1-safe end-to-end smoke of the telemetry pipeline: the real CLI
and bench drivers, run as subprocesses on the CPU backend at toy size,
must emit schema-valid manifest records — including the in-graph per-sweep
event stream — and the summary tool must render them.

This is the CI gate for the whole chain: solver emission sites ->
obs.metrics dispatch -> obs.manifest JSONL -> scripts/telemetry_summary.
"""

import json
import os
import subprocess
import sys
from pathlib import Path

import pytest

ROOT = Path(__file__).resolve().parent.parent

from svd_jacobi_tpu.obs import manifest  # noqa: E402

# Each smoke boots the real CLI/bench driver as a fresh subprocess
# (cold jit caches, full recompile) — slow lane; the in-process
# telemetry contracts live in test_obs.py and stay tier-1.
pytestmark = pytest.mark.slow


def _run(cmd, cwd=None):
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)           # no virtual-device fan-out
    env["JAX_PLATFORMS"] = "cpu"
    return subprocess.run(cmd, capture_output=True, text=True, env=env,
                          cwd=cwd or ROOT, timeout=600)


def test_cli_telemetry_end_to_end(tmp_path):
    p = _run([sys.executable, "-m", "svd_jacobi_tpu.cli", "64",
              "--matrix", "dense", "--no-selftest", "--telemetry",
              "--max-sweeps", "16", "--report-dir", str(tmp_path)])
    assert p.returncode == 0, p.stderr[-800:]
    solve = json.loads(p.stdout.strip().splitlines()[-1])
    assert solve["sweeps"] >= 1

    records = manifest.load(tmp_path / "manifest.jsonl")
    assert len(records) == 1
    rec = records[0]
    manifest.validate(rec)
    assert rec["kind"] == "cli"
    assert rec["environment"]["backend"] == "cpu"
    # Per-stage wall times and the fused solve's per-sweep stream.
    assert {s["name"] for s in rec["stages"]} >= {"warmup_compile", "solve"}
    sweeps = [e for e in rec["telemetry"] if e["event"] == "sweep"]
    assert len(sweeps) == rec["solve"]["sweeps"]
    offs = [e["off_rel"] for e in sweeps]
    assert offs[-1] == min(offs)         # converging trajectory

    # The summary tool renders and validates it.
    p = _run([sys.executable, str(ROOT / "scripts" / "telemetry_summary.py"),
              str(tmp_path / "manifest.jsonl"), "--validate"])
    assert p.returncode == 0, p.stderr[-800:]
    p = _run([sys.executable, str(ROOT / "scripts" / "telemetry_summary.py"),
              str(tmp_path / "manifest.jsonl"), "--last"])
    assert p.returncode == 0 and "telemetry:" in p.stdout


def test_bench_telemetry_end_to_end(tmp_path):
    mpath = tmp_path / "bench.jsonl"
    p = _run([sys.executable, str(ROOT / "bench.py"), "96", "float32",
              "--reps=1", "--oracle=off", "--no-baseline", "--telemetry",
              f"--manifest={mpath}", "--platform=cpu"])
    assert p.returncode == 0, p.stderr[-800:]
    row = json.loads(p.stdout.strip().splitlines()[-1])
    assert row["value"] > 0

    records = manifest.load(mpath)
    assert len(records) == 1
    rec = records[0]
    manifest.validate(rec)
    assert rec["kind"] == "bench"
    assert rec["solve"]["sweeps"] == row["sweeps"]
    sweeps = [e for e in rec["telemetry"] if e["event"] == "sweep"]
    # The untimed telemetered solve re-runs the same deterministic solve.
    assert len(sweeps) == row["sweeps"]


def test_bench_manifest_off(tmp_path):
    p = _run([sys.executable, str(ROOT / "bench.py"), "96", "float32",
              "--reps=1", "--oracle=off", "--no-baseline",
              "--manifest=off", "--platform=cpu"], cwd=tmp_path)
    assert p.returncode == 0, p.stderr[-800:]
    assert not (tmp_path / "reports").exists()
