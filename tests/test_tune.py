"""The measured autotuner (svd_jacobi_tpu/tune/): table machinery,
resolution semantics, the measured-crossover regressions, the TUNE001
analysis pass, and the `-m tune` smoke search lane.

Contract under test (ISSUE/ROADMAP "Measured autotuner"):
  * every "auto" knob resolves through ONE deterministic table lookup;
  * a missing/corrupt/bypassed table reproduces the historical
    hand-picked defaults exactly (loud fallback, never a crash);
  * the SHIPPED table pins the measured verdicts of PROFILE.md items
    17-18 — a regeneration that flips one is a failing test here (a loud
    diff), not a silent default change.
"""

import json
import os
import subprocess
import sys
from pathlib import Path

import jax.numpy as jnp
import numpy as np
import pytest

import svd_jacobi_tpu as sj
from svd_jacobi_tpu import SVDConfig, solver
from svd_jacobi_tpu import tune
from svd_jacobi_tpu.analysis import tune_checks
from svd_jacobi_tpu.obs import manifest
from svd_jacobi_tpu.tune import search, tables

BAD_TABLE = Path(__file__).parent / "fixtures" / "tune_bad_table.json"
BENCH = str(Path(__file__).resolve().parent.parent / "bench.py")


@pytest.fixture(autouse=True)
def _restore_active_table():
    """Every test leaves the process-wide active table as it found it."""
    yield
    tune.set_active_table(None)


def _legacy_block_size(n):
    """The pre-table `pick_block_size` if-ladder, verbatim — the oracle
    for 'missing-table behavior equals the hand-picked defaults'."""
    if n >= 8192:
        return 256
    if n >= 2048:
        return 128
    b = 1
    while b * 16 <= n and b < 128:
        b *= 2
    return b


# ---------------------------------------------------------------------------
# Table machinery.


class TestTableMachinery:
    def test_schema_round_trip(self, tmp_path):
        rows = [
            {"match": {"n_class": "large", "aspect": "square",
                       "dtype": "float32"},
             "knobs": {"block_size": 256}},
            {"match": {}, "knobs": dict(tables.GENERIC_KNOBS)},
        ]
        path = tmp_path / "t.json"
        written = tables.save_table(path, table_id="rt-test", rows=rows,
                                    provenance="round trip")
        loaded = tables.load_table(path)
        assert loaded.table_id == "rt-test"
        assert loaded.sha256 == written.sha256
        assert loaded.rows == written.rows
        # And the loaded table resolves like the in-memory one.
        a = written.resolve(16384, m=16384, dtype="float32",
                            backend="tpu", device_kind="x")
        b = loaded.resolve(16384, m=16384, dtype="float32",
                           backend="tpu", device_kind="x")
        assert a == b and a.block_size == 256

    def test_content_hash_mismatch_is_loud(self, tmp_path):
        payload = tables.save_table(
            tmp_path / "t.json", table_id="hash-test",
            rows=[{"match": {}, "knobs": dict(tables.GENERIC_KNOBS)}],
        ).to_payload()
        payload["rows"][0]["knobs"]["block_size"] = 512   # edit, no re-hash
        bad = tmp_path / "edited.json"
        bad.write_text(json.dumps(payload))
        with pytest.raises(tables.TableError, match="content_sha256"):
            tables.load_table(bad)

    def test_corrupt_table_falls_back_loudly_never_crashes(self):
        """The shipped failing fixture: hand-edited without re-hashing.
        Activating it WARNS and falls back to the builtin generic row —
        resolution keeps working with the hand-picked defaults."""
        with pytest.warns(RuntimeWarning, match="falling back"):
            tune.set_active_table(BAD_TABLE)
        for n in (96, 2048, 8192, 16384):
            assert tune.resolve(n, m=n).block_size == _legacy_block_size(n)

    def test_env_var_table_and_off(self, tmp_path, monkeypatch):
        path = tmp_path / "env.json"
        tables.save_table(path, table_id="env-test", rows=[
            {"match": {"n_class": "medium"}, "knobs": {"block_size": 64}},
            {"match": {}, "knobs": dict(tables.GENERIC_KNOBS)},
        ])
        monkeypatch.setenv("SVDJ_TUNING_TABLE", str(path))
        assert tune.resolve(4096, m=4096).block_size == 64
        monkeypatch.setenv("SVDJ_TUNING_TABLE", "off")
        assert tune.resolve(4096, m=4096).block_size == 128

    def test_invalid_rows_rejected(self, tmp_path):
        for bad_rows, msg in [
            ([{"match": {"n_class": "huge"}, "knobs": {}}], "n_class"),
            ([{"match": {}, "knobs": {"block_size": 0}}], "block_size"),
            ([{"match": {}, "knobs": {"mixed_store": "f16"}}],
             "mixed_store"),
            ([{"match": {}, "knobs": {"batch_tiers": []}}], "batch_tiers"),
            # Tier 1 (the non-coalesced dispatch) is mandatory: without
            # it a lone request would zero-pad into a larger tier.
            ([{"match": {}, "knobs": {"batch_tiers": [4, 16]}}],
             "must include tier 1"),
            # "double" is a fused-single-solve-only mode the stepper/
            # batched/mesh lanes cannot run — never a table value.
            ([{"match": {}, "knobs": {"precondition": "double"}}],
             "precondition"),
            ([{"match": {"shape": "2048"}, "knobs": {}}], "unknown match"),
        ]:
            with pytest.raises(tables.TableError, match=msg):
                tables.save_table(tmp_path / "bad.json",
                                  table_id="x", rows=bad_rows)

    def test_resolution_deterministic_across_processes(self):
        """Same inputs + same table content => byte-identical resolution
        in a fresh interpreter (PYTHONHASHSEED deliberately varied — set
        iteration order must not leak into the result)."""
        probe = (
            "import json;"
            "from svd_jacobi_tpu.tune import tables;"
            "t = tables.load_table(tables.shipped_table_path());"
            "out = [t.resolve(n, m=m, dtype=d, backend=b, device_kind=k)"
            "       for n, m, d, b, k in ["
            "  (96, 96, 'float32', 'cpu', 'cpu'),"
            "  (2048, 2048, 'float32', 'tpu', 'TPU v5 lite'),"
            "  (8192, 8192, 'float32', 'tpu', 'TPU v5 lite'),"
            "  (8192, 131072, 'float32', 'tpu', 'TPU v5 lite'),"
            "  (512, 512, 'float64', 'cpu', 'cpu')]];"
            "print(json.dumps([list(r) for r in out]))"
        )
        outs = []
        for seed in ("0", "1"):
            env = dict(os.environ, JAX_PLATFORMS="cpu",
                       PYTHONHASHSEED=seed, SVDJ_SKIP_GRAFTCHECK="1")
            p = subprocess.run([sys.executable, "-c", probe], env=env,
                               capture_output=True, text=True, timeout=120)
            assert p.returncode == 0, p.stderr[-500:]
            outs.append(p.stdout.strip())
        assert outs[0] == outs[1]

    def test_missing_table_equals_hand_picked_defaults(self):
        """`--tuning-table=off` (builtin generic row) reproduces the
        legacy ladder and the legacy auto-routing exactly."""
        tune.set_active_table("off")
        cfg = SVDConfig()
        for n in (4, 16, 48, 64, 96, 256, 512, 1024, 2047, 2048, 4096,
                  8191, 8192, 16384, 65536):
            assert cfg.pick_block_size(n) == _legacy_block_size(n)
        a32 = jnp.zeros((96, 96), jnp.float32)
        tiny = jnp.zeros((48, 32), jnp.float32)
        a64 = jnp.zeros((96, 96), jnp.float64)
        assert solver._resolve_options(a32, cfg, True)[2:] == \
            ("pallas", "rel")
        assert solver._resolve_options(tiny, cfg, True)[2:] == \
            ("hybrid", "rel")
        assert solver._resolve_options(tiny, cfg, False)[2:] == \
            ("gram-eigh", "abs")
        assert solver._resolve_options(a64, cfg, True)[2:] == \
            ("qr-svd", "rel")
        assert solver._resolve_options(
            a32, SVDConfig(criterion="abs"), True)[2:] == ("hybrid", "abs")

    def test_mistuned_table_cannot_break_capability_guards(self):
        """A table proposing pallas for f64 / tiny shapes is coerced by
        the solver's guards, not obeyed into an invalid program."""
        t = tables.TuningTable(
            table_id="mistuned", sha256="0" * 64,
            rows=({"match": {}, "knobs": {**tables.GENERIC_KNOBS,
                                          "pair_solver": "pallas"}},))
        tune.set_active_table(t)
        a64 = jnp.zeros((96, 96), jnp.float64)
        tiny = jnp.zeros((48, 32), jnp.float32)
        assert solver._resolve_options(a64, SVDConfig(), True)[2] == "qr-svd"
        assert solver._resolve_options(tiny, SVDConfig(), True)[2] == \
            "hybrid"
        # gram-eigh pinned for a factor-computing solve upgrades to
        # hybrid (gram-eigh alone cannot deliver an orthogonal U);
        # sigma-only keeps the cheap path.
        t2 = tables.TuningTable(
            table_id="mistuned2", sha256="0" * 64,
            rows=({"match": {}, "knobs": {**tables.GENERIC_KNOBS,
                                          "pair_solver": "gram-eigh"}},))
        tune.set_active_table(t2)
        a32 = jnp.zeros((96, 96), jnp.float32)
        assert solver._resolve_options(a32, SVDConfig(), True)[2] == "hybrid"
        assert solver._resolve_options(a32, SVDConfig(), False)[2] == \
            "gram-eigh"


# ---------------------------------------------------------------------------
# Measured-crossover regressions: the shipped table's verdicts are pinned
# CONTENT — a regeneration that flips one fails here, loudly.


class TestShippedTableVerdicts:
    @pytest.fixture(scope="class")
    def shipped(self):
        return tables.load_table(tables.shipped_table_path())

    V5E = {"backend": "tpu", "device_kind": "TPU v5 lite"}

    def test_block_256_for_fused_square_n_ge_8192(self, shipped):
        # PROFILE.md item 18: 16384^2 34.8 vs 39.0 s, 8192^2 5.53 vs 5.65.
        for n in (8192, 16384, 32768):
            r = shipped.resolve(n, m=n, dtype="float32", **self.V5E)
            assert r.block_size == 256, (n, r)
        # 32768x8192 (m/n = 4) carries the square verdict too.
        assert shipped.resolve(8192, m=32768, dtype="float32",
                               **self.V5E).block_size == 256

    def test_block_128_below_8192_and_tall_skinny(self, shipped):
        # item 18: 2048^2/4096^2 and 65536x4096 keep b=128.
        for m, n in ((2048, 2048), (4096, 4096), (65536, 4096)):
            r = shipped.resolve(n, m=m, dtype="float32", **self.V5E)
            assert r.block_size == 128, ((m, n), r)
        # Tall-skinny (m >= 8n) keeps 128 even at large n.
        assert shipped.resolve(8192, m=65536, dtype="float32",
                               **self.V5E).block_size == 128

    def test_mixed_store_auto_is_f32(self, shipped):
        # PROFILE.md item 17: f32-store 6.27 s vs bf16 6.47 / bf16g 6.66.
        for kwargs in (self.V5E, {"backend": "cpu", "device_kind": "cpu"}):
            assert shipped.resolve(8192, m=8192, dtype="float32",
                                   **kwargs).mixed_store == "f32"

    def test_f64_routes_qr_svd(self, shipped):
        r = shipped.resolve(512, m=512, dtype="float64", **self.V5E)
        assert r.pair_solver == "qr-svd"

    def test_cpu_medium_square_routes_block_rotation(self, shipped):
        # r03 (PROFILE.md item 29): the blocked-rotation lane wins the
        # CPU medium square class; TPU classes and the CPU small class
        # keep the pallas kernel lane (fallback semantics).
        cpu = {"backend": "cpu", "device_kind": "cpu"}
        assert shipped.resolve(2048, m=2048, dtype="float32",
                               **cpu).pair_solver == "block_rotation"
        assert shipped.resolve(4096, m=4096, dtype="float32",
                               **cpu).pair_solver == "block_rotation"
        # Narrow verdict: tall aspect and the small class stay on the
        # measured pallas default.
        assert shipped.resolve(2048, m=65536, dtype="float32",
                               **cpu).pair_solver == "pallas"
        assert shipped.resolve(512, m=512, dtype="float32",
                               **cpu).pair_solver == "pallas"
        # r05: the TPU v5-lite medium/large square f32 classes route to
        # the VMEM-resident grouped-round lane (R=4 medium; R=2 large —
        # the largest residency whose factor stacks fit the scoped VMEM
        # budget at b=256, per ops.pallas_resident.footprint).
        med = shipped.resolve(2048, m=2048, dtype="float32", **self.V5E)
        assert med.pair_solver == "resident"
        assert med.rounds_resident == 4
        large = shipped.resolve(8192, m=8192, dtype="float32", **self.V5E)
        assert large.pair_solver == "resident"
        assert large.rounds_resident == 2

    def test_solver_consumes_shipped_verdicts(self):
        """End-to-end: `_plan_entry` on a (spoofed-large) problem takes
        the table width. Exercised at the plan level (no 8192^2 solve on
        CPU): pick_block_size is what `_plan` consults."""
        cfg = SVDConfig()
        assert cfg.pick_block_size(8192, m=8192) == 256
        assert cfg.pick_block_size(8192, m=65536) == 128
        assert cfg.pick_block_size(4096, m=65536) == 128

    def test_shipped_table_covers_default_serve_buckets(self, shipped):
        from svd_jacobi_tpu.config import DEFAULT_SERVE_BUCKETS
        from svd_jacobi_tpu.serve import as_bucket
        for spec in DEFAULT_SERVE_BUCKETS:
            b = as_bucket(spec)
            r = shipped.resolve(b.n, m=b.m, dtype=b.dtype, backend="cpu",
                                device_kind="cpu",
                                k=(b.k if b.kind == "topk" else None))
            assert not r.generic_only, (b, r)
            if b.kind == "topk":
                # The truncated family's extension: the sketch knobs
                # themselves must come from a measured k-class row.
                assert not r.sketch_generic_only, (b, r)


# ---------------------------------------------------------------------------
# Serving-layer resolution: once per bucket at declaration.


class TestServeResolution:
    def test_bucket_configs_resolved_at_declaration(self):
        from svd_jacobi_tpu.serve import SVDService, ServeConfig
        cfg = ServeConfig(buckets=((64, 48, "float32"), (96, 64, "float32")),
                          solver=SVDConfig())
        svc = SVDService(cfg)
        for b in svc.buckets:
            resolved = svc._solver_for(b)
            want = tune.resolve(b.n, m=b.m, dtype=b.dtype)
            assert resolved.block_size == want.block_size
            assert resolved.mixed_store == want.mixed_store
        # Explicit user knobs always win over the table.
        cfg2 = ServeConfig(buckets=((64, 48, "float32"),),
                           solver=SVDConfig(block_size=6,
                                            mixed_store="bf16"))
        svc2 = SVDService(cfg2)
        b = next(iter(svc2.buckets))
        assert svc2._solver_for(b).block_size == 6
        assert svc2._solver_for(b).mixed_store == "bf16"

    def test_auto_batch_tiers_resolve_per_bucket(self):
        from svd_jacobi_tpu.serve import SVDService, ServeConfig
        cfg = ServeConfig(buckets=((64, 48, "float32"),),
                          batch_tiers="auto", max_batch=4)
        svc = SVDService(cfg)
        b = next(iter(svc.buckets))
        assert svc._tiers_for(b) == tuple(sorted(set(
            tune.resolve(b.n, m=b.m, dtype=b.dtype).batch_tiers)))

    def test_resolved_config_serves_identically(self):
        """A request served through the resolved per-bucket config equals
        the direct solve (the resolution is a relabeling of the auto
        path, not a numerical change)."""
        from svd_jacobi_tpu.serve import SVDService, ServeConfig
        from svd_jacobi_tpu.utils import matgen
        a = matgen.random_dense(40, 32, seed=7, dtype=jnp.float32)
        with SVDService(ServeConfig(
                buckets=((48, 36, "float32"),))) as svc:
            res = svc.submit(a).result(timeout=600.0)
        assert res.status.name == "OK"
        direct = sj.svd(jnp.pad(a, ((0, 8), (0, 4))))
        # Host-stepped (serve) vs fused solve: same f32 accuracy class,
        # not bit-identical — compare at the class's tolerance.
        np.testing.assert_allclose(np.asarray(res.s),
                                   np.asarray(direct.s)[:32],
                                   rtol=1e-4, atol=1e-5)


# ---------------------------------------------------------------------------
# TUNE001 analysis pass: clean on the repo, fires on its seeded fixtures.


class TestTune001:
    def test_shipped_tables_validate(self):
        assert tune_checks.check_tables() == []

    def test_fixture_table_fires(self):
        findings = tune_checks.check_tables(paths=[BAD_TABLE])
        assert findings and findings[0].code == "TUNE001"
        assert "content_sha256" in findings[0].message

    def test_bucket_coverage_clean_on_shipped(self):
        assert tune_checks.check_bucket_resolution() == []

    def test_bucket_coverage_fires_on_generic_only(self):
        findings = tune_checks.check_bucket_resolution(
            table=tables.builtin_table())
        from svd_jacobi_tpu.config import DEFAULT_SERVE_BUCKETS
        assert len(findings) == len(DEFAULT_SERVE_BUCKETS)
        assert all(f.code == "TUNE001" for f in findings)

    def test_resolved_serve_case_clean(self):
        findings, report = tune_checks.run_resolved_serve_case()
        assert findings == [], [f.render() for f in findings]
        assert set(report["resolved_configs"]) == {"64x48:float32",
                                                   "96x64:float32"}

    def test_resolved_serve_case_fires_when_underdeclared(self):
        """The seeded failing direction: FRESH buckets (cold jit cache)
        with the budget under-declared at 1 — the guard must fire."""
        findings, _ = tune_checks.run_resolved_serve_case(
            expected_problems=1,
            buckets=((72, 52, "float32"), (112, 72, "float32")),
            shapes=((72, 52), (60, 44), (112, 72), (100, 60)))
        assert findings and all(f.code == "TUNE001" for f in findings)


# ---------------------------------------------------------------------------
# Manifest "tune" records.


class TestTuneManifest:
    def test_build_tune_round_trip(self, tmp_path):
        rec = manifest.build_tune(
            m=96, n=64, dtype="float32",
            key={"n_class": "small", "aspect": "square",
                 "dtype": "float32", "backend": "cpu",
                 "device_kind": "cpu"},
            baseline={"knobs": {"block_size": 8}, "time_s": 0.01,
                      "reps": 2, "ok": True, "note": ""},
            grid=[{"knobs": {"block_size": 4}, "time_s": 0.02,
                   "reps": 2, "ok": True, "note": ""}],
            winner={"block_size": 8},
            table_id="t", table_sha256="a" * 64)
        manifest.validate(rec)
        path = manifest.append(tmp_path / "m.jsonl", rec)
        loaded = manifest.load(path)
        assert loaded[0]["kind"] == "tune"
        assert loaded[0]["winner"] == {"block_size": 8}
        assert "tune search" in manifest.summarize(loaded[0])

    def test_build_tune_rejects_malformed_grid(self):
        with pytest.raises(ValueError, match="grid"):
            manifest.build_tune(
                m=1, n=1, dtype="float32", key={}, baseline={},
                grid=[{"time_s": 1.0}],   # no knobs
                winner={}, table_id="t", table_sha256="a" * 64)


# ---------------------------------------------------------------------------
# The `-m tune` smoke lane: a bounded search really runs, writes a
# loadable table, and leaves reconstructable manifest records.


@pytest.mark.tune
def test_smoke_search_end_to_end(tmp_path):
    from svd_jacobi_tpu.tune.__main__ import main as tune_main
    out = tmp_path / "table.json"
    man = tmp_path / "manifest.jsonl"
    rc = tune_main(["--smoke", "--out", str(out), "--manifest", str(man),
                    "--reps", "1", "--budget-s", "5"])
    assert rc == 0
    table = tables.load_table(out)
    assert len(table.rows) >= 2          # >= 1 winner row + generic
    assert table.rows[-1]["match"] == {}
    # The winners resolve (the written table is usable as --tuning-table).
    r = table.resolve(64, m=96, dtype="float32")
    assert r.block_size >= 1
    records = manifest.load(man)
    assert len(records) == len(search.SMOKE_SHAPES)
    for rec in records:
        manifest.validate(rec)
        assert rec["kind"] == "tune"
        assert rec["table_sha256"] == table.sha256
        assert rec["baseline"]["ok"]
        # Provenance: every searched point carries knobs + outcome.
        assert all("knobs" in p for p in rec["grid"])


# ---------------------------------------------------------------------------
# bench.py satellites: the bounded transient retry and --tuning-table.


def _run_bench(*args, env_extra=None):
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env.update(env_extra or {})
    return subprocess.run(
        [sys.executable, BENCH, *args, "--platform=cpu", "--manifest=off"],
        capture_output=True, text=True, env=env, timeout=600)


@pytest.mark.slow
class TestBenchSatellites:
    """bench.py subprocess smokes (fresh process per run = full cold
    recompile) — slow lane; the retry logic itself is unit-style and
    cheap, the subprocess boot is the cost."""

    def test_transient_failure_retries_once_and_notes_it(self):
        p = _run_bench("64", "--novec", "--no-baseline", "--reps=1",
                       "--retry-backoff-s=0",
                       env_extra={"SVDJ_BENCH_CHAOS_TRANSIENT": "1"})
        assert p.returncode == 0, p.stderr[-500:]
        row = json.loads(p.stdout.strip().splitlines()[-1])
        assert row["value"] > 0
        assert row["retried"]["reason"] == "UNAVAILABLE"
        assert "retrying once" in p.stderr

    def test_persistent_transient_failure_emits_error_row(self):
        p = _run_bench("64", "--novec", "--no-baseline", "--reps=1",
                       "--retry-backoff-s=0",
                       env_extra={"SVDJ_BENCH_CHAOS_TRANSIENT": "9"})
        assert p.returncode == 0, p.stderr[-500:]
        row = json.loads(p.stdout.strip().splitlines()[-1])
        assert row["value"] is None and row["retried"] is not None

    def test_clean_run_has_no_retry_note(self):
        p = _run_bench("64", "--novec", "--no-baseline", "--reps=1")
        assert p.returncode == 0, p.stderr[-500:]
        row = json.loads(p.stdout.strip().splitlines()[-1])
        assert "retried" not in row

    def test_tuning_table_flag_off_and_pinned(self, tmp_path):
        path = tmp_path / "pin.json"
        tables.save_table(path, table_id="pin", rows=[
            {"match": {}, "knobs": dict(tables.GENERIC_KNOBS)}])
        for flag in ("--tuning-table=off", f"--tuning-table={path}"):
            p = _run_bench("64", "--novec", "--no-baseline", "--reps=1",
                           flag)
            assert p.returncode == 0, (flag, p.stderr[-500:])
            assert json.loads(
                p.stdout.strip().splitlines()[-1])["value"] > 0
