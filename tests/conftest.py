"""Test backend: 8 virtual CPU devices, fp64 enabled.

SURVEY.md section 4: the reference could only test multi-node on the real
cluster; we exercise all mesh/ppermute logic on a virtual 8-device CPU
backend (`--xla_force_host_platform_device_count=8`) so the full distributed
path runs in CI with no TPU attached.
"""

import os
from pathlib import Path

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)

# Subprocess drills (tests/_*_worker.py) run the package from a bare
# `python tests/_x_worker.py` child; script-mode sys.path holds the
# SCRIPT's directory, not the repo root, so without an installed package
# the child dies on `import svd_jacobi_tpu` before the drill starts.
# Export the repo root once so every spawned child inherits it.
_REPO_ROOT = str(Path(__file__).resolve().parent.parent)
os.environ["PYTHONPATH"] = (
    _REPO_ROOT + os.pathsep + os.environ["PYTHONPATH"]
    if os.environ.get("PYTHONPATH") else _REPO_ROOT)

import jax

# The axon TPU plugin registers itself via sitecustomize and ignores
# JAX_PLATFORMS from the environment; force CPU through the config API.
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

import pytest  # noqa: E402


def pytest_sessionstart(session):
    """graftcheck fail-fast: run the cheap static passes (AST lint +
    jaxpr contract checks over every entry point, mesh included — the
    conftest backend already has 8 virtual devices) BEFORE any test, so a
    contract violation aborts the tier-1 session in seconds instead of
    surfacing as a mysterious failure 140 tests in. The HLO/recompile
    passes run as ordinary tests (tests/test_analysis.py) and via
    `python -m svd_jacobi_tpu.analysis`. Escape hatch (debugging the
    analyzer itself): SVDJ_SKIP_GRAFTCHECK=1.
    """
    if os.environ.get("SVDJ_SKIP_GRAFTCHECK"):
        return
    from svd_jacobi_tpu.analysis import ast_lint, jaxpr_checks, render_findings
    from svd_jacobi_tpu.analysis.concurrency import static_lint
    findings = ast_lint.lint_package()
    findings += jaxpr_checks.check_default_entries(include_mesh=True)
    # graftlock static rules (CONC001/CONC003 + lock-inventory
    # completeness): pure AST, no jax — cheap enough for every session.
    findings += static_lint.lint_package()
    if findings:
        raise pytest.UsageError(render_findings(
            findings,
            header=(f"graftcheck: {len(findings)} contract violation(s) — "
                    f"failing fast before the test run "
                    f"(SVDJ_SKIP_GRAFTCHECK=1 to bypass):")))


@pytest.fixture(scope="session")
def eight_devices():
    assert len(jax.devices()) == 8
    return jax.devices()
