"""Test backend: 8 virtual CPU devices, fp64 enabled.

SURVEY.md section 4: the reference could only test multi-node on the real
cluster; we exercise all mesh/ppermute logic on a virtual 8-device CPU
backend (`--xla_force_host_platform_device_count=8`) so the full distributed
path runs in CI with no TPU attached.
"""

import os

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)

import jax

# The axon TPU plugin registers itself via sitecustomize and ignores
# JAX_PLATFORMS from the environment; force CPU through the config API.
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

import pytest  # noqa: E402


def pytest_sessionstart(session):
    """graftcheck fail-fast: run the cheap static passes (AST lint +
    jaxpr contract checks over every entry point, mesh included — the
    conftest backend already has 8 virtual devices) BEFORE any test, so a
    contract violation aborts the tier-1 session in seconds instead of
    surfacing as a mysterious failure 140 tests in. The HLO/recompile
    passes run as ordinary tests (tests/test_analysis.py) and via
    `python -m svd_jacobi_tpu.analysis`. Escape hatch (debugging the
    analyzer itself): SVDJ_SKIP_GRAFTCHECK=1.
    """
    if os.environ.get("SVDJ_SKIP_GRAFTCHECK"):
        return
    from svd_jacobi_tpu.analysis import ast_lint, jaxpr_checks, render_findings
    findings = ast_lint.lint_package()
    findings += jaxpr_checks.check_default_entries(include_mesh=True)
    if findings:
        raise pytest.UsageError(render_findings(
            findings,
            header=(f"graftcheck: {len(findings)} contract violation(s) — "
                    f"failing fast before the test run "
                    f"(SVDJ_SKIP_GRAFTCHECK=1 to bypass):")))


@pytest.fixture(scope="session")
def eight_devices():
    assert len(jax.devices()) == 8
    return jax.devices()
