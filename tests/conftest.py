"""Test backend: 8 virtual CPU devices, fp64 enabled.

SURVEY.md section 4: the reference could only test multi-node on the real
cluster; we exercise all mesh/ppermute logic on a virtual 8-device CPU
backend (`--xla_force_host_platform_device_count=8`) so the full distributed
path runs in CI with no TPU attached.
"""

import os

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)

import jax

# The axon TPU plugin registers itself via sitecustomize and ignores
# JAX_PLATFORMS from the environment; force CPU through the config API.
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def eight_devices():
    assert len(jax.devices()) == 8
    return jax.devices()
