"""The `-m sanitized` lane: the fused hot paths run under JAX's runtime
sanitizers — jax_debug_nans + jax_debug_infs (NaN/Inf screening of jit
outputs) and jax_transfer_guard="disallow" (implicit host<->device
transfers raise) — combined with the retrace budget guard. This is the
runtime half of graftcheck: the jaxpr/HLO passes prove the structure is
right; this lane proves the structure EXECUTES without host syncs, NaNs,
or cache-key churn on both the single-device and mesh hot paths.

These tests are in the normal tier-1 selection too (not marked slow);
``-m sanitized`` selects just this lane.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import svd_jacobi_tpu as sj
from svd_jacobi_tpu import SVDConfig
from svd_jacobi_tpu.analysis import recompile_guard
from svd_jacobi_tpu.analysis.sanitize import sanitized
from svd_jacobi_tpu.utils import matgen

pytestmark = pytest.mark.sanitized


@pytest.fixture
def sanitizers():
    """Sanitizer context for the duration of one test. Restores config on
    exit; sanitizer state is jit-cache-relevant, so entries touched here
    compile fresh inside (expected, budgeted below)."""
    with sanitized():
        yield


def _ref_sigma(a):
    return np.linalg.svd(np.asarray(a, np.float64), compute_uv=False)


def _check(r, a, rtol):
    s = np.asarray(jax.device_get(r.s), np.float64)
    np.testing.assert_allclose(s, _ref_sigma(a), rtol=rtol, atol=rtol)


def test_single_device_pallas_path(sanitizers):
    """Kernel path (QR-preconditioned, sigma refinement): solve + repeat
    under sanitizers, zero retrace-budget violations."""
    a = matgen.random_dense(96, 96, seed=11, dtype=jnp.float32)
    cfg = SVDConfig(max_sweeps=24, pair_solver="pallas")
    with recompile_guard.RecompileGuard() as guard:
        guard.expect("solver._svd_pallas", problems=1)
        r = sj.svd(a, config=cfg)
        r2 = sj.svd(a, config=cfg)           # repeat: must be a cache hit
        findings = guard.check()
    assert findings == [], [f.render() for f in findings]
    _check(r, a, 1e-4)
    np.testing.assert_array_equal(np.asarray(r.s), np.asarray(r2.s))


def test_single_device_hybrid_path(sanitizers):
    a = matgen.random_dense(48, 48, seed=12, dtype=jnp.float32)
    cfg = SVDConfig(max_sweeps=24, pair_solver="hybrid")
    with recompile_guard.RecompileGuard() as guard:
        guard.expect("solver._svd_padded", problems=1)
        r = sj.svd(a, config=cfg)
        sj.svd(a, config=cfg)
        findings = guard.check()
    assert findings == []
    _check(r, a, 1e-4)


def test_single_device_f64_path(sanitizers):
    a = matgen.random_dense(48, 48, seed=13, dtype=jnp.float64)
    r = sj.svd(a, config=SVDConfig(max_sweeps=24))
    _check(r, a, 1e-8)


def test_mesh_path(sanitizers, eight_devices):
    """The sharded hot path under sanitizers + retrace budget: the
    ppermute ring loop must run transfer-free and compile once."""
    from svd_jacobi_tpu.parallel import sharded
    a = matgen.random_dense(96, 96, seed=14, dtype=jnp.float32)
    cfg = SVDConfig(max_sweeps=24)
    with recompile_guard.RecompileGuard() as guard:
        guard.expect("sharded._svd_sharded_jit", problems=1)
        r = sharded.svd(a, config=cfg)
        sharded.svd(a, config=cfg)
        findings = guard.check()
    assert findings == [], [f.render() for f in findings]
    _check(r, a, 1e-4)


def test_sigma_only_donated(sanitizers):
    """NoVec + donated input: the aliased buffer solve is sanitizer-clean
    (and the caller's array is consumed, as documented)."""
    a = matgen.random_dense(64, 64, seed=15, dtype=jnp.float32)
    a_host = np.asarray(a)
    cfg = SVDConfig(max_sweeps=24, pair_solver="pallas", donate_input=True)
    r = sj.svd(a, compute_u=False, compute_v=False, config=cfg)
    s = np.asarray(jax.device_get(r.s), np.float64)
    np.testing.assert_allclose(s, _ref_sigma(a_host), rtol=1e-4, atol=1e-4)


def test_sanitize_context_restores_state():
    prev_nans = jax.config.jax_debug_nans
    prev_infs = jax.config.jax_debug_infs
    with sanitized():
        assert jax.config.jax_debug_nans and jax.config.jax_debug_infs
    assert jax.config.jax_debug_nans == prev_nans
    assert jax.config.jax_debug_infs == prev_infs


def test_debug_nans_actually_fires(sanitizers):
    """Prove the lane is armed: a NaN-producing jit raises here."""
    with pytest.raises(FloatingPointError):
        jax.jit(lambda x: x / 0.0 * 0.0)(jnp.zeros(4))
