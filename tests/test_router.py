"""Federated replica router lane (serve.router): consistent-hash
routing, journal exclusivity, replica-death rescue, probe recovery, and
the ROUTE001 contract — plus the real-SIGKILL subprocess drill
(tests/_router_worker.py) in the chaos+slow lane."""

import json
import os
import signal
import subprocess
import sys
import time
from pathlib import Path

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from svd_jacobi_tpu import SVDConfig  # noqa: E402
from svd_jacobi_tpu.obs import manifest  # noqa: E402
from svd_jacobi_tpu.resilience import chaos  # noqa: E402
from svd_jacobi_tpu.serve import (AdmissionError, AdmissionReason,  # noqa: E402
                                  HashRing, Journal, JournalLockedError,
                                  ReplicaRouter, ReplicaState, RouterConfig,
                                  ServeConfig, SpoolReplica, SVDService,
                                  input_digest)
from svd_jacobi_tpu.utils import matgen  # noqa: E402

pytestmark = pytest.mark.router

BUCKETS = ((32, 32, "float64"), (48, 32, "float64"))
SOLVER = SVDConfig(block_size=4)


def _serve_cfg(**over):
    base = dict(buckets=BUCKETS, solver=SOLVER, max_queue_depth=32,
                brownout_sigma_only_at=2.0, brownout_shed_at=2.0,
                result_cache_bytes=16 << 20)
    base.update(over)
    return ServeConfig(**base)


def _router_cfg(tmp_path, **over):
    base = dict(replicas=2, serve=_serve_cfg(),
                state_dir=str(tmp_path / "router-state"),
                supervise_interval_s=0.02, heartbeat_timeout_s=0.6,
                probe_interval_s=0.05, probe_timeout_s=120.0)
    base.update(over)
    return RouterConfig(**base)


def _mat(m, n, seed):
    return np.asarray(matgen.random_dense(m, n, seed=seed,
                                          dtype=jnp.float64))


def _sref(a):
    return np.linalg.svd(np.asarray(a, np.float64), compute_uv=False)


def _routed_replica(router, request_id):
    recs = [r for r in router.records() if r.get("event") == "route"
            and r.get("request_id") == request_id]
    assert recs, f"no route record for {request_id}"
    return recs[-1]["replica"]


def _wait(pred, timeout=30.0, interval=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return False


# ---------------------------------------------------------------------------
# Consistent-hash ring.


class TestHashRing:
    def test_deterministic_and_complete(self):
        r1 = HashRing((0, 1, 2), vnodes=64)
        r2 = HashRing((0, 1, 2), vnodes=64)
        for i in range(32):
            d = input_digest(np.full((4, 3), i, np.float32))
            assert r1.preference("32x32:float64", d) == \
                r2.preference("32x32:float64", d)
            assert sorted(r1.preference("32x32:float64", d)) == [0, 1, 2]
        assert r1.preference("48x32:float64") == \
            r2.preference("48x32:float64")

    def test_resubmit_lands_on_owner(self):
        ring = HashRing((0, 1), vnodes=64)
        a = _mat(30, 24, seed=5)
        b = np.asarray(a, order="F")      # same bytes, different layout
        assert input_digest(a) == input_digest(b)
        assert ring.owner("32x32:float64", input_digest(a)) == \
            ring.owner("32x32:float64", input_digest(b))

    def test_minimal_disruption_on_departure(self):
        full = HashRing((0, 1, 2), vnodes=64)
        reduced = HashRing((1, 2), vnodes=64)
        for i in range(64):
            d = input_digest(np.full((2, 2), i, np.float32))
            if full.owner("b", d) != 0:
                assert reduced.owner("b", d) == full.owner("b", d)

    def test_validation(self):
        with pytest.raises(ValueError, match="vnodes"):
            HashRing((0, 1), vnodes=0)
        with pytest.raises(ValueError, match="duplicate"):
            HashRing((0, 0))


# ---------------------------------------------------------------------------
# Journal exclusivity (satellite).


class TestJournalLock:
    def test_second_live_opener_fails_loudly(self, tmp_path):
        p = tmp_path / "j.jsonl"
        j1 = Journal(p, exclusive=True)
        with pytest.raises(JournalLockedError, match="LIVE"):
            Journal(p, exclusive=True)
        j1.release()
        Journal(p, exclusive=True).release()   # relockable after release

    def test_dead_owner_lock_breaks_automatically(self, tmp_path):
        p = tmp_path / "j.jsonl"
        # A lockfile whose owner pid is gone (a SIGKILL'd process): the
        # successor must break it unattended, loudly.
        (tmp_path / "j.jsonl.lock").write_text(json.dumps(
            {"pid": 2 ** 22 + 1234567, "boot_id": "some-other-boot",
             "token": "dead", "t_wall": 0.0}))
        with pytest.warns(RuntimeWarning, match="stale lock"):
            j = Journal(p, exclusive=True)
        assert j.locked
        j.release()

    def test_break_lock_overrides_live_owner(self, tmp_path):
        p = tmp_path / "j.jsonl"
        j1 = Journal(p, exclusive=True)
        assert Journal.break_lock(p) is True
        j2 = Journal(p, exclusive=True)      # the rescuer's fresh lock
        # The dead owner's eventual cleanup must NOT delete the
        # rescuer's lock (token mismatch).
        j1.release()
        assert (tmp_path / "j.jsonl.lock").exists()
        j2.release()
        assert not (tmp_path / "j.jsonl.lock").exists()

    def test_nonexclusive_scan_coexists_with_live_owner(self, tmp_path):
        p = tmp_path / "j.jsonl"
        j1 = Journal(p, exclusive=True)
        assert Journal(p).scan().unfinalized == []   # read surface
        j1.release()

    def test_two_live_services_one_path_refused(self, tmp_path):
        jpath = str(tmp_path / "j.jsonl")
        svc = SVDService(_serve_cfg(journal_path=jpath))
        with pytest.raises(JournalLockedError):
            SVDService(_serve_cfg(journal_path=jpath))
        svc.start()
        svc.stop(timeout=30.0)
        # stop() released the lock: a successor service can claim it.
        SVDService(_serve_cfg(journal_path=jpath)).journal.release()

    @pytest.mark.chaos
    def test_cross_process_live_owner_refused(self, tmp_path):
        """The subprocess half of the satellite: a lock held by a LIVE
        sibling process refuses this process's opener."""
        p = tmp_path / "j.jsonl"
        child = subprocess.Popen(
            [sys.executable, "-c",
             "import sys, time\n"
             "from svd_jacobi_tpu.serve.journal import Journal\n"
             f"j = Journal({str(p)!r}, exclusive=True)\n"
             "print('locked', flush=True)\n"
             "time.sleep(60)\n"],
            stdout=subprocess.PIPE, text=True,
            cwd=str(Path(__file__).resolve().parent.parent))
        try:
            assert child.stdout.readline().strip() == "locked"
            with pytest.raises(JournalLockedError, match="LIVE"):
                Journal(p, exclusive=True)
        finally:
            child.kill()
            child.wait(timeout=30)
        # The owner is dead now: the opener auto-breaks its stale lock.
        with pytest.warns(RuntimeWarning, match="stale lock"):
            Journal(p, exclusive=True).release()


# ---------------------------------------------------------------------------
# Ticket digest exposure (satellite).


class TestTicketDigest:
    def test_digest_on_ticket_and_record(self):
        with SVDService(_serve_cfg(result_cache_bytes=0,
                                   compute_digest=True)) as svc:
            a = _mat(30, 24, seed=3)
            t = svc.submit(a, request_id="dg-0")
            res = t.result(timeout=300.0)
            assert res.status is not None and res.status.name == "OK"
            assert t.digest == input_digest(a)
            rec = [r for r in svc.records() if r.get("kind") == "serve"
                   and r["request"]["id"] == "dg-0"][0]
            assert rec["digest"] == t.digest
            manifest.validate(rec)       # schema round-trip

    def test_digest_off_by_default(self):
        with SVDService(_serve_cfg(result_cache_bytes=0)) as svc:
            t = svc.submit(_mat(30, 24, seed=3))
            t.result(timeout=300.0)
            assert t.digest is None

    def test_cache_hit_ticket_carries_digest(self):
        with SVDService(_serve_cfg()) as svc:
            a = _mat(30, 24, seed=4)
            svc.submit(a).result(timeout=300.0)
            t2 = svc.submit(a)
            res2 = t2.result(timeout=30.0)
            assert res2.path == "cache"
            assert t2.digest == input_digest(a)

    def test_build_serve_digest_round_trip(self):
        rec = manifest.build_serve(
            request_id="x", m=4, n=3, dtype="float32", bucket="b",
            queue_wait_s=0.0, solve_time_s=0.1, status="OK", path="base",
            breaker="closed", brownout="FULL", digest="ab" * 32)
        manifest.validate(rec)
        assert rec["digest"] == "ab" * 32
        with pytest.raises(ValueError):
            manifest.validate({**rec, "digest": 7})


# ---------------------------------------------------------------------------
# Metrics listener ephemeral port (satellite).


@pytest.mark.obs
class TestEphemeralMetricsPort:
    def test_two_replicas_one_host_distinct_ports(self):
        cfgs = [_serve_cfg(metrics=True, metrics_port=0)
                for _ in range(2)]
        svcs = [SVDService(c).start() for c in cfgs]
        try:
            ports = []
            for svc in svcs:
                hz = svc.healthz()
                assert hz["http"] is not None and hz["http"]["port"] > 0
                assert svc.stats()["http_port"] == hz["http"]["port"]
                ports.append(hz["http"]["port"])
            assert ports[0] != ports[1]
        finally:
            for svc in svcs:
                svc.stop(timeout=30.0)

    def test_router_aggregates_metrics_targets(self, tmp_path):
        cfg = _router_cfg(tmp_path,
                          serve=_serve_cfg(metrics=True, metrics_port=0))
        with ReplicaRouter(cfg) as router:
            targets = router.metrics_targets()
            assert len(targets) == 2
            assert len({p for _, p in targets}) == 2

    def test_federated_metrics_one_scrape_target(self, tmp_path):
        """`ReplicaRouter.metrics_text()` + `start_http`: ONE scrape
        target for the federation — every replica's exposition re-emitted
        with a replica label, # HELP/# TYPE dedup'd per family, plus the
        router's own gauges, all behind a single listener."""
        import urllib.request
        cfg = _router_cfg(tmp_path, metrics=True,
                          serve=_serve_cfg(metrics=True))
        with ReplicaRouter(cfg) as router:
            host, port = router.start_http()
            assert router.healthz()["http"]["port"] == port
            text = urllib.request.urlopen(
                f"http://{host}:{port}/metrics", timeout=10
            ).read().decode()
            # Router-side families and replica-side families coexist.
            assert "svdj_replica_state" in text
            assert "svdj_queue_depth" in text
            for i in range(len(router.replicas)):
                assert f'replica="{i}"' in text
            # HELP/TYPE dedup'd: one header per family across N replicas.
            for header in ("# HELP svdj_queue_depth",
                           "# TYPE svdj_queue_depth"):
                assert sum(1 for ln in text.splitlines()
                           if ln.startswith(header)) == 1
            # Family lines stay contiguous (the text format's rule).
            fam_lines = [i for i, ln in enumerate(text.splitlines())
                         if ln.startswith("svdj_queue_depth")]
            assert fam_lines == list(range(fam_lines[0],
                                           fam_lines[0] + len(fam_lines)))
            hz = json.loads(urllib.request.urlopen(
                f"http://{host}:{port}/healthz", timeout=10).read())
            assert hz["ok"] and len(hz["replicas"]) == 2
        assert router.http_address is None    # stop() closed the listener


# ---------------------------------------------------------------------------
# Federated serving.


class TestRouterServing:
    def test_routes_and_matches_oracle(self, tmp_path):
        with ReplicaRouter(_router_cfg(tmp_path)) as router:
            mats = [_mat(30, 24, seed=i) for i in range(4)]
            tickets = [router.submit(m, deadline_s=300.0) for m in mats]
            for m, t in zip(mats, tickets):
                res = t.result(timeout=300.0)
                assert res.status is not None and res.status.name == "OK"
                assert np.abs(np.asarray(res.s) - _sref(m)).max() < 1e-10
                assert t.digest == input_digest(m)
            # Deterministic: the route records agree with the ring.
            for m, t in zip(mats, tickets):
                assert _routed_replica(router, t.request_id) == \
                    router.ring.owner("32x32:float64", input_digest(m))

    def test_resubmit_hits_owner_cache(self, tmp_path):
        with ReplicaRouter(_router_cfg(tmp_path)) as router:
            a = _mat(30, 24, seed=9)
            t1 = router.submit(a, deadline_s=300.0)
            assert t1.result(timeout=300.0).status.name == "OK"
            t2 = router.submit(a, deadline_s=300.0)
            res2 = t2.result(timeout=60.0)
            assert res2.path == "cache"     # zero dispatch on the owner
            assert _routed_replica(router, t2.request_id) == \
                _routed_replica(router, t1.request_id)

    def test_failover_past_quarantined_replica(self, tmp_path):
        with ReplicaRouter(_router_cfg(tmp_path)) as router:
            a = _mat(30, 24, seed=11)
            owner = router.ring.owner("32x32:float64", input_digest(a))
            router.replicas[owner].state = ReplicaState.QUARANTINED
            t = router.submit(a, deadline_s=300.0)
            assert t.result(timeout=300.0).status.name == "OK"
            served = _routed_replica(router, t.request_id)
            assert served != owner
            rec = [r for r in router.records()
                   if r.get("event") == "route"
                   and r.get("request_id") == t.request_id][-1]
            assert rec["failover"] is True and rec["owner"] == owner
            router.replicas[owner].state = ReplicaState.ACTIVE

    def test_no_replica_is_loud(self, tmp_path):
        with ReplicaRouter(_router_cfg(tmp_path)) as router:
            for r in router.replicas:
                r.state = ReplicaState.QUARANTINED
            with pytest.raises(AdmissionError) as ei:
                router.submit(_mat(30, 24, seed=1))
            assert ei.value.reason is AdmissionReason.NO_REPLICA
            for r in router.replicas:
                r.state = ReplicaState.ACTIVE

    def test_client_fault_not_failed_over(self, tmp_path):
        with ReplicaRouter(_router_cfg(tmp_path)) as router:
            with pytest.raises(AdmissionError) as ei:
                router.submit(np.ones((500, 400)))
            assert ei.value.reason is AdmissionReason.NO_BUCKET

    def test_healthz_federated_view(self, tmp_path):
        with ReplicaRouter(_router_cfg(tmp_path)) as router:
            hz = router.healthz()
            assert hz["active"] == 2 and hz["quarantined"] == 0
            assert set(hz["ring"]) == {"32x32:float64", "48x32:float64"}
            assert all(s["journal"] for s in hz["replicas"])
            assert router.ready()

    def test_per_replica_journals_are_distinct_and_locked(self, tmp_path):
        with ReplicaRouter(_router_cfg(tmp_path)) as router:
            paths = {r.journal_path for r in router.replicas}
            assert len(paths) == 2
            for p in paths:
                with pytest.raises(JournalLockedError):
                    Journal(p, exclusive=True)


# ---------------------------------------------------------------------------
# Replica chaos: kill -> evict -> journal rescue -> probe recovery.


@pytest.mark.chaos
class TestReplicaChaos:
    def test_kill_replica_rescues_and_recovers(self, tmp_path):
        with ReplicaRouter(_router_cfg(tmp_path)) as router:
            mats = [_mat(30, 24, seed=20 + i) for i in range(5)]
            victim_idx = router.ring.owner("32x32:float64",
                                           input_digest(mats[0]))
            # The probe RESPAWNS the victim with a fresh service whose
            # in-memory records start empty (a real process restart
            # loses them too; the manifest file is the durable stream)
            # — hold the pre-kill service to audit its records.
            victim_service = router.replicas[victim_idx].service
            with chaos.slow_solve(0.3, shots=64):
                with chaos.kill_replica(victim_idx):
                    tickets = [router.submit(m, deadline_s=600.0)
                               for m in mats]
                results = [t.result(timeout=600.0) for t in tickets]
            for m, res in zip(mats, results):
                assert res.error is None and res.status.name == "OK"
                assert np.abs(np.asarray(res.s) - _sref(m)).max() < 1e-10
            # The rescue reconstructs from the router stream.
            events = router.records()
            trans = [r for r in events
                     if r.get("event") == "replica_transition"]
            assert any(r["to_state"] == "quarantined"
                       and r["replica"] == victim_idx for r in trans)
            rescues = [r for r in events if r.get("event") == "rescue"
                       and r.get("replica") == victim_idx]
            assert rescues and rescues[0]["count"] >= 1
            # Rescued requests carry path="replica_rescue" in the
            # RECEIVING replica's serve records.
            survivor = router.replicas[1 - victim_idx]
            rescued_paths = [r for r in survivor.service.records()
                             if r.get("kind") == "serve"
                             and r.get("path") == "replica_rescue"]
            assert len(rescued_paths) >= 1
            # Exactly-once: every submitted id terminal exactly once
            # across BOTH replicas' serve streams.
            ids = [t.request_id for t in tickets]

            def terminal_map():
                # The ticket unblocks BEFORE the worker appends its
                # serve record (finalize-then-record) — give the last
                # append a moment to land before auditing the stream.
                out = {}
                for recs in (victim_service.records(),
                             survivor.service.records()):
                    for r in recs:
                        if (r.get("kind") == "serve"
                                and r["request"]["id"] in ids):
                            out[r["request"]["id"]] = \
                                out.get(r["request"]["id"], 0) + 1
                return out
            assert _wait(lambda: set(terminal_map()) == set(ids),
                         timeout=10.0)
            terminal = terminal_map()
            assert all(terminal.get(i, 0) == 1 for i in ids), terminal
            # Offline timeline: the federation edges (ring verdict +
            # rescue) join the rescued request's causal story.
            from svd_jacobi_tpu.obs.spans import timeline_from_manifest
            rescued_rid = rescues[0]["request_ids"][0]
            stream = (router.records() + victim_service.records()
                      + survivor.service.records())
            names = [e["name"]
                     for e in timeline_from_manifest(stream, rescued_rid)]
            assert "route" in names and "rescue" in names
            assert "finalize" in names
            # Outcome-caused recovery: probe returns the victim ACTIVE.
            assert _wait(lambda: router.replicas[victim_idx].state
                         is ReplicaState.ACTIVE, timeout=60.0)
            assert any(r["to_state"] == "active" and
                       r["replica"] == victim_idx for r in
                       [x for x in router.records()
                        if x.get("event") == "replica_transition"])
            # The recovered replica serves again (through the ring).
            t = router.submit(mats[0], deadline_s=300.0)
            assert t.result(timeout=300.0).status.name == "OK"

    def test_wedge_replica_evicts_then_recovers(self, tmp_path):
        cfg = _router_cfg(tmp_path, heartbeat_timeout_s=0.4,
                          step_timeout_s=0.4)
        with ReplicaRouter(cfg) as router:
            a = _mat(30, 24, seed=40)
            victim_idx = router.ring.owner("32x32:float64",
                                           input_digest(a))
            with chaos.slow_solve(0.25, shots=16):
                with chaos.wedge_replica(victim_idx, wedge_s=1.5):
                    t1 = router.submit(a, deadline_s=600.0)
                    t2 = router.submit(_mat(28, 20, seed=41),
                                       deadline_s=600.0)
                    res = [t1.result(timeout=600.0),
                           t2.result(timeout=600.0)]
            assert all(r.error is None and r.status.name == "OK"
                       for r in res)
            assert any(r.get("event") == "replica_transition"
                       and r.get("cause") == "heartbeat_stale"
                       for r in router.records())
            assert _wait(lambda: router.replicas[victim_idx].state
                         is ReplicaState.ACTIVE, timeout=60.0)

    def test_registry_reconstruction_matches_live(self, tmp_path):
        from svd_jacobi_tpu.obs.registry import registry_from_manifest
        cfg = _router_cfg(tmp_path, metrics=True)
        with ReplicaRouter(cfg) as router:
            a = _mat(30, 24, seed=50)
            victim_idx = router.ring.owner("32x32:float64",
                                           input_digest(a))
            with chaos.slow_solve(0.3, shots=32):
                with chaos.kill_replica(victim_idx):
                    tickets = [router.submit(_mat(30, 24, seed=50 + i),
                                             deadline_s=600.0)
                               for i in range(3)]
                [t.result(timeout=600.0) for t in tickets]
            text = router.metrics_text()
            assert "svdj_replica_state" in text
            assert "svdj_ring_owned_buckets" in text
            offline = registry_from_manifest(router.records())
            live_rescued = router.metrics.value(
                "svdj_replica_rescued_total", replica=str(victim_idx))
            off_rescued = offline.value("svdj_replica_rescued_total",
                                        replica=str(victim_idx))
            assert live_rescued == off_rescued and live_rescued >= 1


# ---------------------------------------------------------------------------
# ROUTE001 pass (ring rules; the live rescue rule runs in the analysis
# suite itself).


class TestRouteAnalysisPass:
    def test_ring_rules_clean(self):
        from svd_jacobi_tpu.analysis import route_checks
        assert route_checks.check_ring_determinism() == []
        assert route_checks.check_resubmit_affinity() == []

    def test_seeded_skew_fires(self):
        from svd_jacobi_tpu.analysis import route_checks
        findings = route_checks.check_ring_determinism(seed_skew=True)
        assert findings and all(f.code == "ROUTE001" for f in findings)


# ---------------------------------------------------------------------------
# The real-SIGKILL subprocess drill (chaos + slow: two worker boots +
# a kill + a respawn are tens of seconds on the CPU container).


def _spawn_worker(tmp_path, idx, cache, warmup=True, slow_s=0.0):
    spool = tmp_path / f"spool-{idx}"
    journal = tmp_path / f"journal-{idx}.jsonl"
    argv = [sys.executable,
            str(Path(__file__).resolve().parent / "_router_worker.py"),
            "serve", "--spool", str(spool), "--journal", str(journal),
            "--cache", str(cache), "--replica", str(idx),
            # The runtime fuse is an ORPHAN backstop only — it must
            # comfortably outlive the whole drill, or it reads as a
            # mysterious mid-drill replica death.
            "--max-runtime-s", "900"]
    if warmup:
        argv.append("--warmup")
    if slow_s > 0:
        argv += ["--slow-s", str(slow_s)]
    log = open(tmp_path / f"worker-{idx}.log", "a")
    proc = subprocess.Popen(argv, stdout=log, stderr=log)
    return proc, spool, journal


def _wait_heartbeat(spool, timeout=180.0):
    hb = spool / "heartbeat.json"
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if hb.exists():
            try:
                return json.loads(hb.read_text())
            except json.JSONDecodeError:
                pass
        time.sleep(0.1)
    raise TimeoutError(f"no heartbeat in {spool}")


@pytest.mark.chaos
@pytest.mark.slow
class TestSpoolSigkillDrill:
    def test_sigkill_one_of_two_loaded_replicas(self, tmp_path):
        cache = tmp_path / "shared-cache"
        procs = {}
        try:
            # Replica 0 boots FIRST and populates the shared persistent
            # compile-cache namespace; replica 1 then warm-boots from it.
            p0, spool0, journal0 = _spawn_worker(tmp_path, 0, cache,
                                                 slow_s=0.10)
            hb0 = _wait_heartbeat(spool0)
            procs[0] = p0
            p1, spool1, journal1 = _spawn_worker(tmp_path, 1, cache,
                                                 slow_s=0.10)
            hb1 = _wait_heartbeat(spool1)
            procs[1] = p1
            # Shared cold start: the SECOND boot's warmup reads the
            # namespace replica 0 populated — zero fresh compiles.
            assert hb0["coldstart"] is not None
            assert hb1["coldstart"] is not None
            assert hb1["coldstart"]["fresh_compiles"] == 0, hb1
            assert hb1["coldstart"]["cache_hits"] > 0

            replicas = [
                SpoolReplica(0, spool0, journal0),
                SpoolReplica(1, spool1, journal1),
            ]
            cfg = RouterConfig(
                replicas=2,
                serve=ServeConfig(
                    buckets=((48, 32, "float32"),),
                    solver=SVDConfig(pair_solver="pallas"),
                    max_queue_depth=64,
                    brownout_sigma_only_at=2.0, brownout_shed_at=2.0),
                state_dir=str(tmp_path),
                supervise_interval_s=0.05,
                heartbeat_timeout_s=2.0,
                probe_interval_s=0.5, probe_timeout_s=180.0)
            router = ReplicaRouter(cfg, replicas=replicas).start()
            try:
                rng = np.random.default_rng(0)
                mats = [rng.standard_normal((40, 30)).astype(np.float32)
                        for _ in range(8)]
                tickets = [router.submit(m, deadline_s=600.0,
                                         request_id=f"drill-{i:02d}")
                           for i, m in enumerate(mats)]
                # Wait until the victim holds journaled-but-UNFINALIZED
                # debt (the slow solves keep its queue loaded), then
                # REAL SIGKILL — no cleanup, no final fsync beyond what
                # write-ahead already guaranteed.
                victim = max((0, 1),
                             key=lambda i: len(replicas[i].outstanding))
                vjournal = tmp_path / f"journal-{victim}.jsonl"
                assert _wait(
                    lambda: bool(Journal(vjournal).scan(
                        quarantine=False).unfinalized),
                    timeout=120.0)
                os.kill(procs[victim].pid, signal.SIGKILL)
                procs[victim].wait(timeout=30)

                # Zero lost requests: every ticket terminal, OK == oracle.
                def _results_or_diagnose(timeout=300.0):
                    deadline = time.monotonic() + timeout
                    out = []
                    for t in tickets:
                        try:
                            out.append(t.result(timeout=max(
                                5.0, deadline - time.monotonic())))
                        except TimeoutError:
                            # Postmortem before pytest kills the
                            # workers: thread stacks into the worker
                            # logs, router records to stdout.
                            for p in procs.values():
                                if p.poll() is None:
                                    os.kill(p.pid, signal.SIGUSR1)
                            time.sleep(1.0)
                            print("UNRESOLVED:", t.request_id)
                            for rec in router.records():
                                print({k: rec.get(k) for k in (
                                    "event", "replica", "cause", "count",
                                    "request_ids", "targets", "error",
                                    "ok", "request_id")})
                            print("STATS:", router.stats())
                            for i in (0, 1):
                                log = tmp_path / f"worker-{i}.log"
                                if log.exists():
                                    print(f"--- worker {i} log tail:")
                                    print(log.read_text()[-3000:])
                            raise
                    return out
                results = _results_or_diagnose()
                for m, res in zip(mats, results):
                    assert res.error is None, res
                    assert res.status.name == "OK"
                    sref = np.linalg.svd(np.asarray(m, np.float64),
                                         compute_uv=False)
                    assert np.abs(np.asarray(res.s, np.float64)
                                  - sref).max() < 5e-4
                # Router stayed serviceable and rescued the debt.
                assert router.total_rescues >= 1
                events = router.records()
                assert any(r.get("event") == "rescue"
                           and r.get("count", 0) >= 1 for r in events)
                # Exactly-once, journal-verified, BEFORE the victim is
                # respawned (its recover() compacts the journal again):
                # the victim journal holds finalize tombstones for what
                # it served pre-kill (the rescue's compaction keeps
                # them), the survivor its own admits + finalizes incl.
                # the rescued debt — each drill id finalizes at most
                # once per journal, exactly once across the federation.
                ids = {t.request_id for t in tickets}
                finalized_all = {}
                for jp in (tmp_path / "journal-0.jsonl",
                           tmp_path / "journal-1.jsonl"):
                    recs, _ = manifest.read_jsonl_tolerant(
                        jp, quarantine=False)
                    per = {}
                    for r in recs:
                        if (r.get("kind") == "finalize"
                                and r.get("id") in ids):
                            per[r["id"]] = per.get(r["id"], 0) + 1
                    assert all(c == 1 for c in per.values()), per
                    for rid in per:
                        finalized_all[rid] = finalized_all.get(rid, 0) + 1
                assert set(finalized_all) == ids
                assert all(c == 1 for c in finalized_all.values()), \
                    finalized_all

                # Respawn = a process supervisor restarting the
                # replica; wired only NOW so the drill controls the
                # audit-vs-respawn ordering.
                def respawn():
                    p, _, _ = _spawn_worker(tmp_path, victim, cache,
                                            warmup=True)
                    procs[victim] = p
                replicas[victim]._respawn_cmd = respawn
                # Dead replica recovers to ACTIVE via the probe.
                assert _wait(lambda: replicas[victim].state
                             is ReplicaState.ACTIVE, timeout=240.0)
                # The respawned boot (the NEW pid's heartbeat, not the
                # dead process's stale file) warm-started from the
                # shared cache: zero fresh backend compiles.
                def respawned_hb():
                    try:
                        hb = json.loads(
                            (tmp_path / f"spool-{victim}"
                             / "heartbeat.json").read_text())
                    except (OSError, json.JSONDecodeError):
                        return None
                    return hb if hb.get("pid") == procs[victim].pid \
                        else None
                assert _wait(lambda: respawned_hb() is not None,
                             timeout=120.0)
                hb_re = respawned_hb()
                assert hb_re["coldstart"]["fresh_compiles"] == 0
            finally:
                router.stop(drain=True, timeout=60.0)
        finally:
            for p in procs.values():
                if p.poll() is None:
                    p.kill()
                    p.wait(timeout=30)
