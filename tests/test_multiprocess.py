"""Prove the multi-process bootstrap branch actually works.

Launches TWO separate Python processes on a localhost coordinator, each
with 2 virtual CPU devices; `launch.initialize` must execute the real
`jax.distributed.initialize` branch (not the single-process no-op), the
mesh must span all 4 global devices, and the sharded solve must agree with
a host oracle on the SAME matrix (sharded_random is decomposition-
invariant). This is the TPU-native equivalent of the reference's 2-node
MPI run (build/runSVDMPICUDA.slurm: -N 2; main.cu:1427-1442) — VERDICT r2
missing #4.
"""

import json
import os
import socket
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

# Each test here boots TWO fresh Python processes that recompile the full
# solver stack from cold caches — the cost IS the scenario. Slow lane;
# run with `-m slow` (or no marker filter).
pytestmark = pytest.mark.slow


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_two_process_cpu_cluster(tmp_path):
    worker = Path(__file__).parent / "_mp_worker.py"
    coord = f"127.0.0.1:{_free_port()}"
    outfile = tmp_path / "sigma.json"

    repo_root = str(Path(__file__).parent.parent)
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)  # worker sets cpu via the config API
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    procs = [
        subprocess.Popen(
            [sys.executable, str(worker), coord, str(i), "2", str(outfile)],
            env=env, cwd=str(worker.parent.parent),
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
        for i in range(2)
    ]
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=280)
        outs.append(out)
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {i} failed:\n{out[-3000:]}"

    result = json.loads(outfile.read_text())
    assert result["process_count"] == 2
    assert result["global_devices"] == 4

    # Oracle: the same matrix single-process (decomposition-invariant gen).
    import jax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    from svd_jacobi_tpu.utils import matgen

    mesh1 = Mesh(np.array(jax.devices()[:1]), ("x",))
    a = np.asarray(matgen.sharded_random(
        96, 96, NamedSharding(mesh1, P(None, "x")), seed=11), np.float64)
    s_ref = np.linalg.svd(a, compute_uv=False)
    s = np.asarray(result["s"], np.float64)
    assert np.max(np.abs(s - s_ref)) / s_ref[0] < 5e-6


def test_two_process_checkpoint_kill_and_resume(tmp_path):
    """Multi-host-safe checkpointing (VERDICT r3 missing #3): a 2-process
    cluster snapshots per-process shard files (no host ever gathers the
    non-addressable global arrays), is killed, and a FRESH cluster resumes
    from the per-process files and converges to the host oracle."""
    worker = Path(__file__).parent / "_mp_worker.py"
    outfile = tmp_path / "sigma.json"
    ckpt = tmp_path / "state.npz"

    repo_root = str(Path(__file__).parent.parent)
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")

    def launch(mode):
        coord = f"127.0.0.1:{_free_port()}"
        procs = [
            subprocess.Popen(
                [sys.executable, str(worker), coord, str(i), "2",
                 str(outfile), mode, str(ckpt)],
                env=env, cwd=str(worker.parent.parent),
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
            for i in range(2)
        ]
        outs = [p.communicate(timeout=280)[0] for p in procs]
        for i, (p, out) in enumerate(zip(procs, outs)):
            assert p.returncode == 0, f"worker {i} failed:\n{out[-3000:]}"

    launch("ckpt_save")
    assert (tmp_path / "state.npz.proc0of2").exists()
    assert (tmp_path / "state.npz.proc1of2").exists()
    launch("ckpt_resume")

    result = json.loads(outfile.read_text())
    import jax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    from svd_jacobi_tpu.utils import matgen

    mesh1 = Mesh(np.array(jax.devices()[:1]), ("x",))
    a = np.asarray(matgen.sharded_random(
        96, 96, NamedSharding(mesh1, P(None, "x")), seed=11), np.float64)
    s_ref = np.linalg.svd(a, compute_uv=False)
    s = np.asarray(result["s"], np.float64)
    assert np.max(np.abs(s - s_ref)) / s_ref[0] < 5e-6
    assert not (tmp_path / "state.npz.proc0of2").exists()
