"""The VMEM-resident grouped-round lane (pair_solver="resident").

Covers the PR's acceptance surface: the megakernel (interpret mode) is
BITWISE the iterated-jnp twin given the same factors, R=1 delegates
verbatim to the blocked-rotation sweep, the lane's sigma/U/V match the
pallas lane and the f64 oracle on gap/flat/decaying spectra through the
fused, stepped and batched surfaces, chaos NaN mid-residency decodes
NONFINITE (with batched member isolation), the five new jits keep the
once-per-bucket compile contract (RETRACE001) and ride the AOT ledger
two ways (AOT001 + seeded unbudgeted fixture), the lowered fused entry
carries zero collectives, the cost model's resident byte claim holds
(<= 1/2 of block_rotation per sweep at 2048^2 f32 R>=4), the static
VMEM-budget check is clean with a firing over-budget fixture, and the
over-budget runtime error names the lane and the knob to turn.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import svd_jacobi_tpu as sj
from svd_jacobi_tpu import SVDConfig, solver
from svd_jacobi_tpu.ops import pallas_resident as pr
from svd_jacobi_tpu.ops import rounds
from svd_jacobi_tpu.parallel import schedule as sched
from svd_jacobi_tpu.resilience import chaos

CFG = SVDConfig(pair_solver="resident", block_size=16)

# Redundant-coverage depth rides the slow lane: every demoted case has
# a tier-1 twin asserting the same contract on a cheaper surface (the
# tier-1 suite must stay inside the 870 s ROADMAP budget).
_deep = pytest.mark.slow


def _spectrum_matrix(n, spec, seed=7, dtype=jnp.float32):
    rng = np.random.default_rng(seed)
    if spec == "gap":
        sv = np.concatenate([np.ones(4) * 100.0, np.ones(n - 4)])
    elif spec == "flat":
        sv = np.ones(n)
    else:  # decaying
        sv = np.exp(-np.arange(n) / (n / 8))
    qa, _ = np.linalg.qr(rng.standard_normal((n, n)))
    qb, _ = np.linalg.qr(rng.standard_normal((n, n)))
    return jnp.asarray((qa * sv) @ qb.T, dtype)


def _stacks(k, m, b, seed, dtype=jnp.float32):
    rng = np.random.default_rng(seed)
    top = jnp.asarray(rng.standard_normal((k, m, b)), dtype)
    bot = jnp.asarray(rng.standard_normal((k, m, b)), dtype)
    return top, bot


def _factors(k, m, b, r, seed):
    """Orthogonal (r, k, 2b, 2b) factor stacks via group_factors on a
    real Gram — the factors the lane would actually apply."""
    top, bot = _stacks(k, m, b, seed)
    g = pr._full_gram(top, bot)
    dmax2 = rounds._global_dmax2(top, bot)
    f, _, _, _ = pr.group_factors(g, dmax2, jnp.float32(0.0), r=r, k=k, b=b)
    return top, bot, f


class TestKernelEquivalence:
    @pytest.mark.parametrize("r", [2, 4])
    def test_megakernel_bitwise_vs_iterated_twin(self, r):
        """Given the SAME factor stacks, the interpret-mode megakernel's
        R fused rounds (slot-remap exchange) equal the iterated jnp twin
        (quadrant dot2 + rotate_blocks) BITWISE — the exchange really is
        pure renaming and each mm has the twin's exact shape."""
        k, m, b = 4, 48, 8
        top, bot, f = _factors(k, m, b, r, seed=3)
        kt, kb = pr._apply_group_kernel(top, bot, f, interpret=True)
        tt, tb = pr._apply_group_rounds(top, bot, f)
        np.testing.assert_array_equal(np.asarray(kt), np.asarray(tt))
        np.testing.assert_array_equal(np.asarray(kb), np.asarray(tb))

    @_deep
    def test_megakernel_bitwise_batched(self):
        """Batched (per-member tournament) slot remap: still bitwise."""
        batch, kp, m, b = 2, 3, 40, 8
        k = batch * kp
        top, bot = _stacks(k, m, b, seed=5)
        g = pr._full_gram(top, bot, batch)
        dmax2 = rounds._global_dmax2(top, bot, batch=batch)
        f, _, _, _ = pr.group_factors(g, dmax2, jnp.float32(0.0), r=2,
                                      k=k, b=b, batch=batch)
        kt, kb = pr._apply_group_kernel(top, bot, f, batch=batch,
                                        interpret=True)
        tt, tb = pr._apply_group_rounds(top, bot, f, batch=batch)
        np.testing.assert_array_equal(np.asarray(kt), np.asarray(tt))
        np.testing.assert_array_equal(np.asarray(kb), np.asarray(tb))

    def test_composed_twin_matches_iterated(self):
        """The composed-W twin (one GEMM) matches the iterated rounds to
        f32 contraction accuracy (not bitwise: different add order)."""
        k, m, b = 3, 32, 8
        top, bot, f = _factors(k, m, b, 4, seed=7)
        ct, cb = pr._apply_group_composed(top, bot, f)
        tt, tb = pr._apply_group_rounds(top, bot, f)
        scale = float(jnp.max(jnp.abs(top))) + float(jnp.max(jnp.abs(bot)))
        np.testing.assert_allclose(np.asarray(ct), np.asarray(tt),
                                   rtol=0, atol=3e-5 * scale)
        np.testing.assert_allclose(np.asarray(cb), np.asarray(tb),
                                   rtol=0, atol=3e-5 * scale)

    def test_exchange_matches_schedule(self):
        """Identity factors make the group pass a PURE exchange chain:
        R rounds of the slot remap must equal R `schedule.rotate_blocks`
        tournament rotations, bitwise."""
        k, m, b, r = 4, 24, 8, 3
        top, bot = _stacks(k, m, b, seed=11)
        eye = jnp.broadcast_to(jnp.eye(2 * b, dtype=jnp.float32),
                               (r, k, 2 * b, 2 * b))
        kt, kb = pr._apply_group_kernel(top, bot, eye, interpret=True)
        et, eb = top, bot
        for _ in range(r):
            et, eb = sched.rotate_blocks(et, eb)
        np.testing.assert_array_equal(np.asarray(kt), np.asarray(et))
        np.testing.assert_array_equal(np.asarray(kb), np.asarray(eb))

    def test_r1_delegates_to_block_sweep_bitwise(self):
        """sweep_resident at R=1 IS rounds.sweep_block, bitwise — the
        delegation is literal, not re-derived."""
        k, m, b = 3, 48, 8
        top, bot = _stacks(k, m, b, seed=13)
        dmax2 = rounds._global_dmax2(top, bot)
        rtol = jnp.float32(1e-6)
        rt, rb_, _, _, roff = pr.sweep_resident(
            top, bot, None, None, dmax2, rtol, r_rounds=1, interpret=True)
        st, sb, _, _, soff = rounds.sweep_block(
            top, bot, None, None, dmax2, rtol, interpret=True)
        np.testing.assert_array_equal(np.asarray(rt), np.asarray(st))
        np.testing.assert_array_equal(np.asarray(rb_), np.asarray(sb))
        assert float(roff) == float(soff)

    def test_gram_carry_matches_fresh_bootstrap(self):
        """After one group the carried G equals a fresh X^T X of the
        group's output panels to f32 contraction accuracy — the carry
        advance (J^T G J + permutation) tracks the real panels."""
        k, m, b, r = 4, 48, 8, 2
        top, bot = _stacks(k, m, b, seed=17)
        g = pr._full_gram(top, bot)
        dmax2 = rounds._global_dmax2(top, bot)
        f, g_out, _, _ = pr.group_factors(g, dmax2, jnp.float32(0.0),
                                          r=r, k=k, b=b)
        nt, nb = pr._apply_group_rounds(top, bot, f)
        g_ref = pr._full_gram(nt, nb)
        scale = float(jnp.max(jnp.abs(g_ref)))
        np.testing.assert_allclose(np.asarray(g_out), np.asarray(g_ref),
                                   rtol=0, atol=2e-5 * scale)


class TestLaneAccuracy:
    @pytest.mark.parametrize("spec", ["gap", "flat", "decaying"])
    def test_matches_pallas_and_oracle(self, spec):
        """sigma/U/V of the resident lane match the pallas lane and the
        f64 oracle on gap/flat/decaying spectra (f32 input)."""
        n = 96
        a = _spectrum_matrix(n, spec)
        r = sj.svd(a, config=CFG)
        assert r.status_enum().name in ("OK", "STAGNATED")
        s_ref = np.linalg.svd(np.asarray(a, np.float64), compute_uv=False)
        serr = np.max(np.abs(np.asarray(r.s, np.float64) - s_ref)) / s_ref[0]
        assert serr < 2e-6
        u, s, v = (np.asarray(r.u, np.float64), np.asarray(r.s, np.float64),
                   np.asarray(r.v, np.float64))
        res = np.linalg.norm(np.asarray(a, np.float64) - (u * s) @ v.T)
        assert res / np.linalg.norm(a) < 5e-6
        assert np.max(np.abs(u.T @ u - np.eye(n))) < 5e-5
        assert np.max(np.abs(v.T @ v - np.eye(n))) < 5e-5
        rp = sj.svd(a, config=SVDConfig(pair_solver="pallas", block_size=16))
        np.testing.assert_allclose(np.asarray(r.s), np.asarray(rp.s),
                                   rtol=1e-5, atol=1e-5 * float(s_ref[0]))

    @_deep
    @pytest.mark.parametrize("rr", [2, 5])
    def test_rounds_resident_knob_respected(self, rr):
        """Explicit rounds_resident values (including one clamped to the
        sweep's round count) converge to the same spectrum."""
        n = 96
        a = _spectrum_matrix(n, "decaying", seed=23)
        cfg = SVDConfig(pair_solver="resident", block_size=16,
                        rounds_resident=rr)
        r = sj.svd(a, config=cfg)
        assert r.status_enum().name in ("OK", "STAGNATED")
        s_ref = np.linalg.svd(np.asarray(a, np.float64), compute_uv=False)
        assert np.max(np.abs(np.asarray(r.s, np.float64) - s_ref)) / \
            s_ref[0] < 2e-6

    def test_rounds_resident_invalid_rejected(self):
        a = jnp.zeros((96, 96), jnp.float32)
        with pytest.raises(ValueError, match="rounds_resident"):
            sj.svd(a, config=SVDConfig(pair_solver="resident",
                                       block_size=16, rounds_resident=0))

    @_deep
    def test_wide_input_transposes(self):
        rng = np.random.default_rng(5)
        a = jnp.asarray(rng.standard_normal((64, 96)), jnp.float32)
        r = sj.svd(a, config=CFG)
        s_ref = np.linalg.svd(np.asarray(a, np.float64), compute_uv=False)
        assert np.max(np.abs(np.asarray(r.s, np.float64) - s_ref)) / \
            s_ref[0] < 2e-6
        assert r.u.shape == (64, 64) and r.v.shape == (96, 64)

    def test_batched_matches_oracle_and_isolates_nan_member(self):
        """The batched lane: per-member sigmas match the oracle; a
        chaos-poisoned member decodes NONFINITE with OK neighbors."""
        rng = np.random.default_rng(9)
        stack = jnp.stack([jnp.asarray(rng.standard_normal((64, 64)),
                                       jnp.float32) for _ in range(3)])
        cfg = SVDConfig(pair_solver="resident", block_size=16)
        r = solver.svd_batched(stack, config=cfg)
        for i in range(3):
            assert int(r.status[i]) == int(solver.SolveStatus.OK)
            s_ref = np.linalg.svd(np.asarray(stack[i], np.float64),
                                  compute_uv=False)
            assert np.max(np.abs(np.asarray(r.s[i], np.float64) - s_ref)) \
                / s_ref[0] < 2e-6
        with chaos.nan_at_sweep(1):
            rn = solver.svd_batched(stack, config=cfg)
        assert int(rn.status[0]) == int(solver.SolveStatus.NONFINITE)
        assert int(rn.status[1]) == int(solver.SolveStatus.OK)
        assert int(rn.status[2]) == int(solver.SolveStatus.OK)

    @_deep
    def test_chaos_nan_mid_residency_decodes_nonfinite(self):
        """NaN injected mid-solve (inside the resident bulk loop, where
        the carried Gram could otherwise launder it) decodes NONFINITE."""
        rng = np.random.default_rng(11)
        a = jnp.asarray(rng.standard_normal((96, 96)), jnp.float32)
        with chaos.nan_at_sweep(1):
            r = sj.svd(a, config=CFG)
        assert r.status_enum() is solver.SolveStatus.NONFINITE


class TestSteppers:
    def test_stepper_matches_fused(self):
        rng = np.random.default_rng(13)
        a = jnp.asarray(rng.standard_normal((96, 96)), jnp.float32)
        rf = sj.svd(a, config=CFG)
        st = solver.SweepStepper(a, config=CFG)
        assert st._kernel_path and st.method == "resident"
        assert st.phase_info().stage == "bulk"
        state = st.init()
        while st.should_continue(state):
            state = st.step(state)
        rs = st.finish(state)
        assert rs.status_enum().name == "OK"
        np.testing.assert_allclose(np.asarray(rs.s), np.asarray(rf.s),
                                   rtol=1e-5, atol=1e-4)

    @_deep
    def test_batched_stepper_matches_fused(self):
        rng = np.random.default_rng(15)
        stack = jnp.stack([jnp.asarray(rng.standard_normal((64, 64)),
                                       jnp.float32) for _ in range(2)])
        cfg = SVDConfig(pair_solver="resident", block_size=16)
        rf = solver.svd_batched(stack, config=cfg)
        bst = solver.BatchedSweepStepper(stack, config=cfg)
        assert bst.method == "resident"
        state = bst.init()
        while bst.should_continue(state):
            state = bst.step(state)
        rb = bst.finish(state)
        for i in range(2):
            assert int(rb.status[i]) == int(solver.SolveStatus.OK)
            np.testing.assert_allclose(np.asarray(rb.s[i]),
                                       np.asarray(rf.s[i]),
                                       rtol=1e-5, atol=1e-4)

    @_deep
    def test_sigma_promote_flow(self):
        rng = np.random.default_rng(17)
        a = jnp.asarray(rng.standard_normal((96, 64)), jnp.float32)
        st = solver.SweepStepper(a, config=CFG)
        state = st.init()
        while st.should_continue(state):
            state = st.step(state)
        full = st.finish(state)
        sig, payload = st.sigma_finish(state)
        assert payload["promotable"]
        np.testing.assert_allclose(np.asarray(sig.s), np.asarray(full.s),
                                   rtol=1e-4, atol=1e-4)
        promoted = solver.finish_from_payload(payload)
        np.testing.assert_allclose(np.asarray(promoted.s),
                                   np.asarray(full.s), rtol=0, atol=0)

    def test_aot_entries_cover_both_stages(self):
        """The stepped surfaces declare the resident BULK jit plus the
        unchanged pallas POLISH jit — the bulk->polish handoff is
        AOT-warmable end to end."""
        rng = np.random.default_rng(19)
        a = jnp.asarray(rng.standard_normal((96, 96)), jnp.float32)
        st = solver.SweepStepper(a, config=CFG)
        names = [n for n, _, _, _ in st.aot_entries()]
        assert "solver._sweep_step_resident_jit" in names
        assert "solver._sweep_step_pallas_jit" in names
        stack = jnp.stack([a, a])
        bst = solver.BatchedSweepStepper(stack, config=CFG)
        bnames = [n for n, _, _, _ in bst.aot_entries()]
        assert "solver._sweep_step_resident_batched_jit" in bnames
        assert "solver._sweep_step_pallas_batched_jit" in bnames


class TestVmemBudget:
    def test_footprint_fields_and_monotonicity(self):
        fp = pr.footprint(2048, 128, 8, 4)
        assert fp["lane"] == "pallas_resident.apply_group"
        assert fp["fits"] and fp["row_chunk"] >= 128
        assert fp["step_bytes"] <= fp["budget_bytes"]
        # Deeper residency monotonically grows the resident set.
        assert (pr.footprint(2048, 128, 8, 8)["step_bytes"]
                > fp["step_bytes"])

    def test_over_budget_raises_named_error(self, monkeypatch):
        """The runtime guard: an over-budget geometry raises
        VmemBudgetError naming the lane, the offending geometry and the
        knob to turn — not a Mosaic compile error."""
        from svd_jacobi_tpu.ops.pallas_apply import VmemBudgetError
        monkeypatch.setattr(pr, "VMEM_STEP_BUDGET", 1024)
        top, bot, f = _factors(2, 24, 8, 2, seed=29)
        with pytest.raises(VmemBudgetError) as ei:
            pr._apply_group_kernel(top, bot, f, interpret=True)
        msg = str(ei.value)
        assert "(m, b, k, R) = (24, 8, 2, 2)" in msg
        assert "rounds_resident" in msg
        assert ei.value.lane == "pallas_resident.apply_group"
        assert ei.value.fallback == "block_rotation"

    def test_vmem_check_clean_and_fixture_fires(self):
        """VMEM001: every shipped geometry (serve buckets + the table's
        TPU resident rows) fits its footprint model; the seeded
        over-budget fixture MUST fire."""
        from svd_jacobi_tpu.analysis import perf_checks
        findings, rows = perf_checks.check_vmem_budget()
        assert findings == []
        # The shipped TPU resident rows are evaluated (not just buckets).
        resident_rows = [r for r in rows
                         if r["lane"] == "pallas_resident.apply_group"]
        assert resident_rows and all(r["fits"] for r in resident_rows)
        assert all(r["envelope_n"] >= r["n"] for r in resident_rows)
        fixture_findings, frows = perf_checks.check_vmem_budget(
            fixture_oversize=True)
        assert any(f.code == "VMEM001"
                   and f.where.startswith("fixture_oversize")
                   for f in fixture_findings)
        assert any(r["source"] == "fixture_oversize" and not r["fits"]
                   for r in frows)

    def test_supported_gate_consistent_with_pick_chunk(self):
        assert pr.supported(2048, 128, 8, 4)
        assert not pr.supported(2048, 120, 8, 4)      # lane alignment
        assert not pr.supported(2048, 128, 8, 10_000)  # over budget


@pytest.mark.serve
class TestServeEscalation:
    def test_vmem_budget_error_routes_to_ladder(self, monkeypatch):
        """A VmemBudgetError out of the base dispatch re-routes the
        request down the escalation ladder (path="ladder", status OK)
        instead of erroring it — and does not trip the breaker."""
        from svd_jacobi_tpu.ops.pallas_apply import VmemBudgetError
        from svd_jacobi_tpu.serve import service as service_mod
        from svd_jacobi_tpu.serve import (BreakerState, ServeConfig,
                                          SVDService)
        from svd_jacobi_tpu.solver import SolveStatus
        from svd_jacobi_tpu.utils import matgen

        calls = {"n": 0}

        def boom(self, lane, req, cu, cv, **kw):
            calls["n"] += 1
            raise VmemBudgetError(
                "no usable VMEM row chunk for the resident megakernel at "
                "(m, b, k, R) = (32, 8, 2, 4); lower rounds_resident",
                lane="pallas_resident.apply_group",
                fallback="block_rotation")

        monkeypatch.setattr(service_mod.SVDService, "_solve_base", boom)
        cfg = ServeConfig(buckets=((32, 32, "float64"),),
                          solver=SVDConfig(block_size=4),
                          max_queue_depth=8)
        a = matgen.random_dense(32, 32, seed=77, dtype=jnp.float64)
        with SVDService(cfg) as svc:
            res = svc.submit(a).result(timeout=180.0)
            health = svc.healthz()
        assert calls["n"] == 1
        assert res.status is SolveStatus.OK
        assert res.path == "ladder"
        s_ref = np.linalg.svd(np.asarray(a, np.float64), compute_uv=False)
        np.testing.assert_allclose(np.asarray(res.s), s_ref,
                                   rtol=1e-10, atol=1e-12)
        # Planning failure, not a backend fault: breaker stays closed,
        # and the escalation is counted for the flight recorder.
        assert health["breaker"] == BreakerState.CLOSED.value
        assert health["stats"]["vmem_escalations"] == 1


class TestAnalysisLedger:
    def test_retrace_once_per_problem(self):
        """Once-per-bucket compiles for the fused resident jit: two
        shapes, two solves each — repeats are pure cache hits."""
        from svd_jacobi_tpu.analysis.recompile_guard import RecompileGuard
        rng = np.random.default_rng(27)
        mats = {n: jnp.asarray(rng.standard_normal((n, n)), jnp.float32)
                for n in (48, 64)}
        cfg = SVDConfig(pair_solver="resident", block_size=8, max_sweeps=8)
        with RecompileGuard() as guard:
            guard.expect("solver._svd_resident", problems=2)
            for n, a in mats.items():
                jax.block_until_ready(sj.svd(a, config=cfg).s)
                jax.block_until_ready(sj.svd(a, config=cfg).s)
        assert guard.check() == []
        traces = guard.new_traces()
        assert traces["solver._svd_resident"] == 2

    def test_aot001_bijection_and_seeded_unbudgeted_entry(self):
        """All five new jits ride the registry/budget bijection; dropping
        one budget fires AOT001 naming it (the seeded fixture)."""
        from svd_jacobi_tpu import config as _config
        from svd_jacobi_tpu.analysis import aot_checks
        from svd_jacobi_tpu.serve import registry
        entries = registry.jit_entries()
        new = ("solver._svd_resident", "solver._svd_resident_donated",
               "solver._svd_resident_batched",
               "solver._sweep_step_resident_jit",
               "solver._sweep_step_resident_batched_jit")
        for name in new:
            assert name in entries
            assert name in _config.RETRACE_BUDGETS
        assert aot_checks.check_budget_coverage() == []
        budgets = {k: v for k, v in _config.RETRACE_BUDGETS.items()
                   if k != "solver._svd_resident"}
        findings = aot_checks.check_budget_coverage(budgets=budgets)
        assert [f.code for f in findings] == ["AOT001"]
        assert findings[0].where == "solver._svd_resident"

    def test_zero_collective_hlo_budget(self):
        """COLLECTIVE_BUDGET["pallas_resident"]: the lowered fused entry
        carries no collectives of any kind."""
        from svd_jacobi_tpu.analysis import entries, hlo_checks
        probes = {p.name: p
                  for p in entries.single_device_probes(include_f64=False)}
        assert "pallas_resident" in probes
        assert probes["pallas_resident"].entry_id == "solver._svd_resident"
        assert hlo_checks.check_collective_budget(
            probes["pallas_resident"]) == []

    def test_tune_axis_and_table_validity(self):
        """rounds_resident is a validated table knob, the shipped table
        routes the TPU v5-lite medium/large square f32 classes onto the
        lane (R=4 medium, R=2 large — the VMEM envelope), CPU routing is
        untouched, and the search axis exists exactly where the kernel
        lane does."""
        from svd_jacobi_tpu.tune import search, tables
        t = tables.TuningTable.from_payload({
            "schema_version": tables.SCHEMA_VERSION,
            "table_id": "t", "rows": [
                {"match": {"n_class": "medium"},
                 "knobs": {"pair_solver": "resident",
                           "rounds_resident": 4}}],
        }, verify_hash=False)
        res = t.resolve(2048, dtype="float32", backend="cpu",
                        device_kind="cpu")
        assert res.pair_solver == "resident" and res.rounds_resident == 4
        with pytest.raises(tables.TableError, match="rounds_resident"):
            tables.TuningTable.from_payload({
                "schema_version": tables.SCHEMA_VERSION,
                "table_id": "bad", "rows": [
                    {"match": {}, "knobs": {"rounds_resident": 0}}],
            }, verify_hash=False)
        shipped = tables.load_table(tables.shipped_table_path())
        med = shipped.resolve(2048, dtype="float32", backend="tpu",
                              device_kind="tpu-v5-lite")
        assert med.pair_solver == "resident" and med.rounds_resident == 4
        large = shipped.resolve(8192, dtype="float32", backend="tpu",
                                device_kind="tpu-v5-lite")
        assert large.pair_solver == "resident"
        assert large.rounds_resident == 2
        assert pr.footprint(8192, large.block_size,
                            8192 // (2 * large.block_size), 2)["fits"]
        cpu_med = shipped.resolve(2048, dtype="float32", backend="cpu",
                                  device_kind="cpu")
        assert cpu_med.pair_solver == "block_rotation"
        assert cpu_med.rounds_resident is None
        axes = dict(search._axes(512, "float32", {}, smoke=False))
        assert "resident" in axes["pair_solver"]
        assert set(axes["rounds_resident"]) == {2, 4, 8}
        axes_f64 = dict(search._axes(512, "float64", {}, smoke=False))
        assert "resident" not in axes_f64["pair_solver"]
        assert "rounds_resident" not in axes_f64

    def test_costmodel_resident_halves_sweep_bytes(self):
        """The acceptance byte claim: at 2048^2 f32 lane geometry the
        resident lane's modeled HBM bytes per sweep are <= 1/2 of
        block_rotation's at R>=4 — and monotonically shrink with R."""
        from svd_jacobi_tpu.obs import costmodel

        def sweep_bytes(solver_name, rr=None):
            phases = costmodel.sweep_costs(
                2048, 2048, block_size=128, pair_solver=solver_name,
                sweeps=1.0, rounds_resident=rr)
            return sum(c.hbm_bytes for c in phases.values())

        base = sweep_bytes("block_rotation")
        r4 = sweep_bytes("resident", 4)
        r8 = sweep_bytes("resident", 8)
        assert r4 <= 0.5 * base
        assert r8 < r4 < sweep_bytes("resident", 2) < base
