"""Truncated top-k + tall-skinny lane tests (ops/sketch.py, solver.svd_topk
/ svd_tall, the serve bucket families, and their analysis contracts).

Oracle discipline: singular VALUES compare against the full solve /
numpy's f64 SVD; singular VECTORS compare through the per-vector
subspace residual ``||A v_i - s_i u_i||`` (vectors are unique only up to
sign/rotation within sigma ties, so elementwise comparison would be
flaky by construction). Tolerances follow the documented accuracy
contract (README "Workloads"): gap spectra tight, smooth geometric decay
at the Halko tail class, flat spectra exact in value.
"""

import numpy as np
import pytest

import jax.numpy as jnp

import svd_jacobi_tpu as sj
from svd_jacobi_tpu import SVDConfig, solver
from svd_jacobi_tpu.ops import sketch
from svd_jacobi_tpu.utils import matgen


def _with_spectrum(m, n, sigmas, seed=0):
    """(m, n) f32 matrix with the given singular values (f64 build)."""
    rng = np.random.default_rng(seed)
    u, _ = np.linalg.qr(rng.standard_normal((m, n)))
    v, _ = np.linalg.qr(rng.standard_normal((n, n)))
    return jnp.asarray((u * np.asarray(sigmas)) @ v.T, jnp.float32)


def _subspace_residual(a, r):
    """max_i ||A v_i - s_i u_i|| / s_i — per-vector accuracy of the
    truncated factors, invariant under sign flips and tie rotations."""
    an = np.asarray(a, np.float64)
    un = np.asarray(r.u, np.float64)
    sn = np.asarray(r.s, np.float64)
    vn = np.asarray(r.v, np.float64)
    res = np.linalg.norm(an @ vn - un * sn[None, :], axis=0)
    return float(np.max(res / np.maximum(sn, 1e-300)))


class TestTsqr:
    def test_chunked_equals_factorization(self):
        a = matgen.random_dense(300, 24, seed=1, dtype=jnp.float32)
        q, r = sketch.tsqr(a, chunk=64)
        qn, rn = np.asarray(q, np.float64), np.asarray(r, np.float64)
        an = np.asarray(a, np.float64)
        assert q.shape == (300, 24) and r.shape == (24, 24)
        np.testing.assert_allclose(qn @ rn, an, atol=2e-6)
        np.testing.assert_allclose(qn.T @ qn, np.eye(24), atol=2e-6)
        # R is triangular up to the sign convention.
        assert np.max(np.abs(np.tril(rn, -1))) < 2e-6

    def test_base_case_matches_dense_qr(self):
        # Short inputs take the dense reduced QR directly.
        a = matgen.random_dense(48, 32, seed=2, dtype=jnp.float32)
        q, r = sketch.tsqr(a)
        qd, rd = jnp.linalg.qr(a)
        np.testing.assert_allclose(np.asarray(q), np.asarray(qd),
                                   atol=1e-6)
        np.testing.assert_allclose(np.asarray(r), np.asarray(rd),
                                   atol=1e-6)

    def test_non_chunk_multiple_rows(self):
        # 300 rows over 64-row chunks: the zero-padded tail chunk.
        a = matgen.random_dense(300, 16, seed=3, dtype=jnp.float32)
        q, r = sketch.tsqr(a, chunk=128)
        qn = np.asarray(q, np.float64)
        np.testing.assert_allclose(qn.T @ qn, np.eye(16), atol=2e-6)
        np.testing.assert_allclose(qn @ np.asarray(r, np.float64),
                                   np.asarray(a, np.float64), atol=2e-6)

    def test_tsqr_jit_nonfinite_flag(self):
        a = matgen.random_dense(256, 16, seed=4, dtype=jnp.float32)
        _, _, nf = solver._tsqr_jit(a, chunk=64)
        assert not bool(nf)
        _, _, nf = solver._tsqr_jit(a.at[5, 3].set(jnp.nan), chunk=64)
        assert bool(nf)

    def test_batched_tsqr_matches_members(self):
        stack = jnp.stack([matgen.random_dense(256, 16, seed=s,
                                               dtype=jnp.float32)
                           for s in (5, 6, 7)])
        qb, rb, nfb = solver._tsqr_batched_jit(stack, chunk=64)
        for j in range(3):
            q1, r1, nf1 = solver._tsqr_jit(stack[j], chunk=64)
            np.testing.assert_allclose(np.asarray(qb[j]), np.asarray(q1),
                                       atol=1e-6)
            np.testing.assert_allclose(np.asarray(rb[j]), np.asarray(r1),
                                       atol=1e-6)
        assert not bool(np.any(np.asarray(nfb)))

    def test_precondition_qr_tall_routes_chunked_and_agrees(self):
        """The Drmac preconditioner's tall path (m >= 8n -> chunked TSQR)
        produces a valid factorization with the same bookkeeping."""
        a = matgen.random_dense(512, 32, seed=8, dtype=jnp.float32)
        q1, r, order, work = solver._precondition_qr(a)
        an = np.asarray(a, np.float64)
        qn, rn = np.asarray(q1, np.float64), np.asarray(r, np.float64)
        on = np.asarray(order)
        np.testing.assert_allclose(qn @ rn, an[:, on], atol=3e-6)
        np.testing.assert_allclose(np.asarray(work), np.asarray(r).T,
                                   atol=1e-6)


class TestSvdTopk:
    def test_gap_spectrum_matches_full_solve(self):
        """The PCA/embedding workload class: rank-k signal over a noise
        floor — the randomized lane recovers values AND vectors at the
        f32 class."""
        m, n, k = 192, 160, 12
        sig = np.concatenate([np.geomspace(1.0, 0.2, k),
                              np.full(n - k, 1e-4)])
        a = _with_spectrum(m, n, sig, seed=10)
        r = solver.svd_topk(a, k)
        assert r.status_enum().name == "OK"
        full = sj.svd(a)
        np.testing.assert_allclose(np.asarray(r.s),
                                   np.asarray(full.s)[:k],
                                   rtol=1e-4, atol=1e-5)
        assert _subspace_residual(a, r) < 1e-3
        assert r.u.shape == (m, k) and r.v.shape == (n, k)

    def test_decaying_spectrum_tolerance_class(self):
        """Smooth geometric decay (no gap): the documented Halko-tail
        class — q power iterations tighten the relative error
        geometrically; q=2 holds 2% on this spectrum."""
        m, n, k = 192, 160, 16
        a = _with_spectrum(m, n, np.geomspace(1.0, 1e-5, n), seed=11)
        s_ref = np.linalg.svd(np.asarray(a, np.float64),
                              compute_uv=False)[:k]
        r = solver.svd_topk(a, k, config=SVDConfig(power_iters=2))
        err = np.max(np.abs(np.asarray(r.s, np.float64) - s_ref) / s_ref)
        assert err < 2e-2, err

    def test_flat_spectrum_values_exact(self):
        """All sigmas equal: any sketch subspace carries the exact
        values (vectors are arbitrary within the tie — not compared)."""
        m, n, k = 192, 160, 16
        a = _with_spectrum(m, n, np.ones(n), seed=12)
        r = solver.svd_topk(a, k)
        np.testing.assert_allclose(np.asarray(r.s), 1.0, atol=1e-5)

    def test_wide_input_transposes(self):
        tall = _with_spectrum(192, 160, np.concatenate(
            [np.geomspace(1.0, 0.3, 8), np.full(152, 1e-4)]), seed=13)
        a = tall.T                               # wide (160, 192)
        r = solver.svd_topk(a, 8)
        assert r.u.shape == (160, 8) and r.v.shape == (192, 8)
        assert _subspace_residual(tall, r._replace(u=r.v, v=r.u)) < 1e-3

    def test_wide_sketch_fallback_is_full_truncation(self):
        """k + oversample >= n: the lane degrades to the full solve
        truncated — identical values."""
        a = matgen.random_dense(64, 24, seed=14, dtype=jnp.float32)
        r = solver.svd_topk(a, 20)            # l = 20 + 8 >= 24
        full = sj.svd(a)
        np.testing.assert_allclose(np.asarray(r.s),
                                   np.asarray(full.s)[:20], rtol=1e-6)

    def test_nan_input_reads_nonfinite(self):
        a = matgen.random_dense(192, 160, seed=15, dtype=jnp.float32)
        r = solver.svd_topk(a.at[3, 4].set(jnp.nan), 8)
        assert r.status_enum().name == "NONFINITE"

    def test_sigma_only(self):
        a = _with_spectrum(128, 96, np.concatenate(
            [np.geomspace(1.0, 0.5, 8), np.full(88, 1e-4)]), seed=16)
        r = solver.svd_topk(a, 8, compute_u=False, compute_v=False)
        assert r.u is None and r.v is None and r.s.shape == (8,)
        assert r.status_enum().name == "OK"

    def test_deterministic(self):
        """Seeded sketch: repeated calls agree bitwise (nothing dynamic
        in the pipeline — the retrace-safety prerequisite)."""
        a = matgen.random_dense(128, 96, seed=17, dtype=jnp.float32)
        r1 = solver.svd_topk(a, 8)
        r2 = solver.svd_topk(a, 8)
        assert np.array_equal(np.asarray(r1.s), np.asarray(r2.s))
        assert np.array_equal(np.asarray(r1.u), np.asarray(r2.u))

    def test_validates_knobs(self):
        a = matgen.random_dense(64, 48, seed=18, dtype=jnp.float32)
        with pytest.raises(ValueError, match="top-k rank"):
            solver.svd_topk(a, 0)
        with pytest.raises(ValueError, match="oversample"):
            solver.svd_topk(a, 4, config=SVDConfig(oversample=0))
        with pytest.raises(ValueError, match="power_iters"):
            solver.svd_topk(a, 4, config=SVDConfig(power_iters=-1))


class TestSvdTall:
    def test_factors_match_oracle(self):
        m, n = 512, 48
        a = matgen.random_dense(m, n, seed=20, dtype=jnp.float32)
        r = solver.svd_tall(a)
        assert r.status_enum().name == "OK"
        an = np.asarray(a, np.float64)
        s_ref = np.linalg.svd(an, compute_uv=False)
        np.testing.assert_allclose(np.asarray(r.s, np.float64), s_ref,
                                   rtol=1e-4, atol=1e-6)
        un, vn = np.asarray(r.u, np.float64), np.asarray(r.v, np.float64)
        recon = un @ np.diag(np.asarray(r.s, np.float64)) @ vn.T
        assert np.linalg.norm(recon - an) / np.linalg.norm(an) < 1e-5
        np.testing.assert_allclose(un.T @ un, np.eye(n), atol=1e-5)
        np.testing.assert_allclose(vn.T @ vn, np.eye(n), atol=1e-5)

    def test_below_threshold_delegates(self):
        a = matgen.random_dense(96, 48, seed=21, dtype=jnp.float32)  # m<8n
        r = solver.svd_tall(a)
        full = sj.svd(a)
        np.testing.assert_allclose(np.asarray(r.s), np.asarray(full.s),
                                   rtol=1e-6)

    def test_wide_transposes(self):
        a = matgen.random_dense(48, 512, seed=22, dtype=jnp.float32)
        r = solver.svd_tall(a)
        assert r.u.shape == (48, 48) and r.v.shape == (512, 48)
        assert r.status_enum().name == "OK"

    def test_nan_input_reads_nonfinite(self):
        a = matgen.random_dense(512, 48, seed=23, dtype=jnp.float32)
        r = solver.svd_tall(a.at[100, 7].set(jnp.nan))
        assert r.status_enum().name == "NONFINITE"

    def test_f64_qr_svd_family(self):
        """The tall lane composes with the f64 qr-svd core (no Pallas
        dependency — TSQR + XLA block solvers)."""
        a = matgen.random_dense(400, 40, seed=24, dtype=jnp.float64)
        r = solver.svd_tall(a)
        s_ref = np.linalg.svd(np.asarray(a), compute_uv=False)
        np.testing.assert_allclose(np.asarray(r.s), s_ref, rtol=1e-12,
                                   atol=1e-13)


@pytest.mark.rank
@pytest.mark.serve
class TestServeRankFamilies:
    BUCKETS = ((64, 48, "float32"), (256, 24, "float32", "tall"),
               (96, 96, "float32", "topk", 8))

    def _cfg(self, **kw):
        from svd_jacobi_tpu.serve import ServeConfig
        kw.setdefault("buckets", self.BUCKETS)
        kw.setdefault("solver", SVDConfig())
        kw.setdefault("brownout_sigma_only_at", 2.0)
        kw.setdefault("brownout_shed_at", 2.0)
        return ServeConfig(**kw)

    def test_routing_families(self):
        from svd_jacobi_tpu.serve import BucketSet
        bs = BucketSet(self.BUCKETS)
        # Full requests never land in the topk bucket...
        b = bs.route(90, 90, "float32")
        assert b is None          # 90x90 fits only the topk bucket
        # ...tall requests take the tall bucket over nothing...
        assert bs.route(250, 20, "float32").kind == "tall"
        # ...top-k requests take ONLY topk buckets with covering k.
        assert bs.route(90, 80, "float32", top_k=5).kind == "topk"
        assert bs.route(90, 80, "float32", top_k=9) is None
        assert bs.route(60, 40, "float32").name == "64x48:float32"

    def test_bucket_spec_validation(self):
        from svd_jacobi_tpu.serve import as_bucket
        assert as_bucket("256x24:float32:tall").kind == "tall"
        assert as_bucket("96x96:float32:topk8").k == 8
        assert as_bucket((96, 96, "float32", "topk", 8)).kind == "topk"
        with pytest.raises(ValueError, match="m >= 8n"):
            as_bucket((64, 48, "float32", "tall"))
        with pytest.raises(ValueError, match="1 <= k <= n"):
            as_bucket((96, 96, "float32", "topk", 200))
        with pytest.raises(ValueError, match="unknown kind"):
            as_bucket((96, 96, "float32", "rank"))

    def test_serve_tall_and_topk_vs_oracle(self):
        from svd_jacobi_tpu.serve import SVDService
        with SVDService(self._cfg()) as svc:
            at = matgen.random_dense(250, 20, seed=30, dtype=jnp.float32)
            rt = svc.submit(at).result(600)
            assert rt.status.name == "OK" and rt.bucket.endswith(":tall")
            s_ref = np.linalg.svd(np.asarray(at, np.float64),
                                  compute_uv=False)
            np.testing.assert_allclose(np.asarray(rt.s, np.float64),
                                       s_ref, rtol=1e-3, atol=1e-5)
            ak = _with_spectrum(90, 80, np.concatenate(
                [np.geomspace(1.0, 0.3, 5), np.full(75, 1e-4)]), seed=31)
            rk = svc.submit(ak, top_k=5).result(600)
            assert rk.status.name == "OK"
            assert rk.bucket == "96x96:float32:topk8"
            assert rk.u.shape == (90, 5) and rk.v.shape == (80, 5)
            sk = np.linalg.svd(np.asarray(ak, np.float64),
                               compute_uv=False)[:5]
            np.testing.assert_allclose(np.asarray(rk.s, np.float64), sk,
                                       rtol=1e-3)

    def test_batched_topk_dispatch_vs_per_request(self):
        """Padded-tier coalesced top-k dispatch: two requests ride ONE
        tier-4 batched solve (zero-padded tail) and must match their
        per-request serve results — the per-member oracle."""
        from svd_jacobi_tpu.serve import SVDService
        mats = [
            _with_spectrum(90, 80, np.concatenate(
                [np.geomspace(1.0, 0.4, 6), np.full(74, 1e-4)]), seed=s)
            for s in (40, 41)]
        serial = {}
        with SVDService(self._cfg()) as svc:
            for j, a in enumerate(mats):
                serial[j] = svc.submit(a, top_k=6).result(600)
                assert serial[j].status.name == "OK"
        with SVDService(self._cfg(max_batch=4, batch_tiers=(1, 4),
                                  batch_window_s=2.0)) as svc:
            svc.warmup(sigma_only=False)
            tickets = [svc.submit(a, top_k=6) for a in mats]
            results = [t.result(600) for t in tickets]
        recs = {r["request"]["id"]: r for r in svc.records()
                if r["status"] == "OK" and not
                r["request"]["id"].startswith("warmup")}
        batch_ids = {recs[t.request_id]["batch_id"] for t in tickets}
        assert len(batch_ids) == 1 and None not in batch_ids
        assert all(recs[t.request_id]["batch_tier"] == 4 for t in tickets)
        assert all(recs[t.request_id]["rank_mode"] == "topk"
                   and recs[t.request_id]["k"] == 6 for t in tickets)
        for j, r in enumerate(results):
            assert r.status.name == "OK"
            np.testing.assert_allclose(np.asarray(r.s),
                                       np.asarray(serial[j].s),
                                       rtol=1e-4, atol=1e-6)
            np.testing.assert_allclose(np.abs(np.asarray(r.v)),
                                       np.abs(np.asarray(serial[j].v)),
                                       rtol=2e-3, atol=2e-4)

    def test_serve_record_carries_rank_fields(self):
        from svd_jacobi_tpu import obs
        rec = obs.manifest.build_serve(
            request_id="r1", m=90, n=80, dtype="float32",
            bucket="96x96:float32:topk8", queue_wait_s=0.0,
            solve_time_s=0.1, status="OK", path="base", breaker="closed",
            brownout="FULL", rank_mode="topk", k=5)
        obs.manifest.validate(rec)
        assert rec["rank_mode"] == "topk" and rec["k"] == 5
        assert "topk[k=5]" in obs.manifest.summarize(rec)

    def test_topk_degraded_sigma_only(self):
        """A SIGMA_ONLY-browned-out top-k request still returns its
        truncated sigmas (factors dropped, degraded=True) — the brownout
        variant of the truncated lane."""
        import time

        from svd_jacobi_tpu.resilience import chaos
        from svd_jacobi_tpu.serve import SVDService
        cfg = self._cfg(buckets=(("96x96:float32:topk8"),),
                        max_queue_depth=10,
                        brownout_sigma_only_at=0.2, brownout_shed_at=2.0)
        with SVDService(cfg) as svc:
            with chaos.stuck_backend(shots=1, max_stall_s=3.0):
                first = svc.submit(matgen.random_dense(
                    90, 80, seed=42, dtype=jnp.float32), top_k=5)
                time.sleep(0.1)            # let it dispatch and stall
                tickets = [svc.submit(matgen.random_dense(
                    90, 80, seed=43 + i, dtype=jnp.float32), top_k=5)
                    for i in range(4)]
                results = [t.result(600) for t in [first] + tickets]
        assert all(r.status.name == "OK" for r in results)
        degraded = [r for r in results if r.degraded]
        assert degraded, "no request was admitted under SIGMA_ONLY"
        for r in degraded:
            assert r.u is None and r.v is None
            assert r.s.shape == (5,)
            assert np.isfinite(np.asarray(r.s)).all()


@pytest.mark.rank
class TestRankAnalysisContracts:
    def test_rank_retrace_case_clean(self):
        from svd_jacobi_tpu.analysis import recompile_guard
        findings, report = recompile_guard.run_serve_rank_case()
        assert findings == [], [f.render() for f in findings]
        assert all(s == "OK" for s in report["serve_statuses"])

    def test_rank_retrace_fires_when_underdeclared(self):
        """Seeded failing fixture: FRESH buckets, budget under-declared
        at 0 problems — the guard must fire (a warm cache would mask a
        per-request/per-k leak)."""
        from svd_jacobi_tpu.analysis import recompile_guard
        findings, _ = recompile_guard.run_serve_rank_case(
            expected_problems=0,
            buckets=((272, 28, "float32", "tall"),
                     (104, 104, "float32", "topk", 6)),
            requests=(((272, 28), None), ((104, 104), 6)))
        assert findings and all(f.code == "RETRACE001" for f in findings)

    def test_tune001_topk_sketch_coverage_fires(self):
        """Seeded failing fixture for the TUNE001 extension: a table
        whose rows cover the bucket's shape class but carry NO k-class
        sketch rows — the topk bucket's sketch knobs resolve generic and
        the rule fires."""
        from svd_jacobi_tpu.analysis import tune_checks
        from svd_jacobi_tpu.tune import tables
        payload = {
            "schema_version": tables.SCHEMA_VERSION,
            "table_id": "no-sketch-rows",
            "rows": [
                {"match": {"n_class": "small"},
                 "knobs": {"block_size": 16}},
                {"match": {}, "knobs": dict(tables.GENERIC_KNOBS)},
            ],
        }
        payload["content_sha256"] = tables.content_hash(payload)
        t = tables.TuningTable.from_payload(payload)
        findings = tune_checks.check_bucket_resolution(
            table=t, buckets=((96, 96, "float32", "topk", 8),))
        assert len(findings) == 1
        assert "SKETCH" in findings[0].message

    def test_tune001_clean_on_shipped_table_with_rank_buckets(self):
        from svd_jacobi_tpu.analysis import tune_checks
        assert tune_checks.check_bucket_resolution() == []

    def test_sketch_probes_zero_collectives(self):
        from svd_jacobi_tpu.analysis import entries, hlo_checks
        probes = {p.name: p for p in entries.sketch_probes()}
        for name in ("sketch_project", "tsqr_tall"):
            assert hlo_checks.check_collective_budget(probes[name]) == []


class TestSearchSketchAxes:
    def test_sketch_axis_sweep_records_points(self):
        """The coordinate-descent sketch sweep on a small eligible shape:
        baseline + grid points recorded, winner never silently less
        accurate (the 2x-accuracy guard)."""
        from svd_jacobi_tpu.tune import search
        a = _with_spectrum(256, 256, np.concatenate(
            [np.geomspace(1.0, 0.2, 32), np.full(224, 1e-4)]), seed=50)
        res = search.ShapeResult(
            m=256, n=256, dtype="float32",
            key={"n_class": "small", "aspect": "square",
                 "dtype": "float32", "backend": "cpu",
                 "device_kind": "cpu"},
            baseline=search.Point(knobs={}), points=[], winner={})
        search._search_sketch_axes(res, a, SVDConfig(), reps=1,
                                   budget_s=30.0, min_gain=0.03)
        assert res.sketch_k == 32
        assert res.sketch_baseline is not None and res.sketch_baseline.ok
        assert res.sketch_points, "no sketch grid points recorded"
        assert set(res.sketch_winner) == {"oversample", "power_iters"}
