"""Subprocess driver for the network-chaos lane (tests/test_transport.py):
a REAL HTTP replica process the partition drill can blackhole behind the
fault proxy or SIGKILL mid-load — run as

    python tests/_http_worker.py serve --journal J --announce A \
        [--cache C] [--warmup] [--replica N] [--max-runtime-s S]

``serve`` runs `serve.transport.run_http_replica`: build the service
(journal write-ahead, shared persistent compile cache), replay the
journal if one exists (a RESPAWNED replica recovers its own remaining
debt), optionally AOT-warm from the shared cache namespace (healthz then
reports the coldstart's ``fresh_compiles`` — the drill asserts a warm
respawn reads 0), bind an ephemeral port, write ``{host, port, pid,
boot_id}`` to the --announce file, then serve until a wire-level stop, a
FENCE (exit 5 — a rescued-away replica must not keep serving), or the
runtime fuse (exit 4). The process is designed to be SIGKILL'd or
partitioned: everything the router's rescue needs (journal + lockfile +
fence token) is on the shared filesystem, nothing in memory matters.
"""

import argparse
import faulthandler
import os
import signal
import sys

# Stuck-worker forensics: `kill -USR1 <pid>` dumps every thread's stack
# to stderr (the drill captures it in worker-<i>.log).
faulthandler.register(signal.SIGUSR1)

os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=2")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

BUCKET = (48, 32, "float32")


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("command", choices=["serve"])
    p.add_argument("--journal", required=True)
    p.add_argument("--announce", required=True,
                   help="file to write the bound {host, port, pid} to "
                        "(ports are ephemeral; the parent reads this)")
    p.add_argument("--cache", default=None)
    p.add_argument("--warmup", action="store_true")
    p.add_argument("--replica", type=int, default=0)
    p.add_argument("--slow-s", type=float, default=0.0,
                   help="per-sweep host delay on every dispatch (widens "
                        "the parent's kill window deterministically)")
    p.add_argument("--max-runtime-s", type=float, default=300.0)
    args = p.parse_args(argv)

    from svd_jacobi_tpu import SVDConfig
    from svd_jacobi_tpu.serve import ServeConfig
    from svd_jacobi_tpu.serve.transport import run_http_replica

    slow_cm = None
    if args.slow_s > 0:
        from svd_jacobi_tpu.resilience import chaos
        # The reference must outlive this function call: a dropped
        # contextmanager is GC'd, which runs its finally and DISARMS
        # the hook.
        slow_cm = chaos.slow_solve(args.slow_s, shots=10 ** 6)
        slow_cm.__enter__()

    config = ServeConfig(
        buckets=(BUCKET,),
        solver=SVDConfig(pair_solver="pallas"),
        journal_path=args.journal,
        compile_cache_dir=args.cache,
        compute_digest=True,
        result_cache_bytes=16 << 20,
        max_queue_depth=64,
        brownout_sigma_only_at=2.0, brownout_shed_at=2.0)
    return run_http_replica(config, warmup=args.warmup,
                            announce_path=args.announce,
                            max_runtime_s=args.max_runtime_s)


if __name__ == "__main__":
    sys.exit(main())
