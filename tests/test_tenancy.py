"""Multi-tenant QoS lane (serve.queue.TenantTable + ServeConfig.tenants):
token-bucket rate limits that reject loudly, weighted-fair dequeue,
per-tenant deadline-budget shares, tenant-isolated result caching, the
no-rejection-leaks-budget audit, per-tenant manifest/journal attribution
(surviving restart recovery), live-vs-offline SLO agreement, and the
adversarial-tenant fairness drills — single-host and through the HTTP
replica router (chaos lane)."""

import json
import time

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from svd_jacobi_tpu import SVDConfig  # noqa: E402
from svd_jacobi_tpu.obs import manifest  # noqa: E402
from svd_jacobi_tpu.obs.registry import (  # noqa: E402
    registry_from_manifest, tenant_slo_from_records)
from svd_jacobi_tpu.resilience import chaos  # noqa: E402
from svd_jacobi_tpu.serve import (AdmissionError, AdmissionQueue,  # noqa: E402
                                  AdmissionReason, Journal, ReplicaRouter,
                                  Request, RouterConfig, ServeConfig,
                                  SVDService)
from svd_jacobi_tpu.serve.buckets import as_bucket  # noqa: E402
from svd_jacobi_tpu.serve.queue import (DEFAULT_TENANT,  # noqa: E402
                                        TenantPolicy, TenantTable,
                                        TokenBucket, as_tenant_policy)
from svd_jacobi_tpu.serve.router import _FAILOVER_REASONS  # noqa: E402
from svd_jacobi_tpu.serve.transport import (HttpReplica,  # noqa: E402
                                            HttpReplicaServer)
from svd_jacobi_tpu.utils import matgen  # noqa: E402

pytestmark = pytest.mark.tenant

BUCKET = (32, 32, "float64")
SOLVER = SVDConfig(block_size=4)


def _cfg(**over):
    base = dict(buckets=(BUCKET,), solver=SOLVER, max_queue_depth=64,
                brownout_sigma_only_at=2.0, brownout_shed_at=2.0)
    base.update(over)
    return ServeConfig(**base)


def _mat(seed, m=28, n=28):
    return matgen.random_dense(m, n, seed=seed, dtype=jnp.float64)


def _mk_req(rid, tenant, bucket=None, deadline=None, submitted=None):
    bucket = as_bucket(BUCKET) if bucket is None else bucket
    return Request(
        id=f"t-{rid}", a=None, m=bucket.m, n=bucket.n,
        orig_shape=(bucket.m, bucket.n), transposed=False, bucket=bucket,
        compute_u=True, compute_v=True, degraded=False,
        deadline=deadline, deadline_s=None,
        submitted=float(rid) if submitted is None else submitted,
        tenant=tenant)


def _slo_totals(snap):
    tot = {"served": 0, "ok": 0, "deadline_miss": 0, "error": 0,
           "shed": 0}
    for c in snap["buckets"].values():
        for k in tot:
            tot[k] += int(c.get(k, 0))
    return tot


# ---------------------------------------------------------------------------
# Policy / token-bucket units.


class TestPolicyUnits:
    def test_policy_validation(self):
        with pytest.raises(ValueError):
            TenantPolicy(weight=0.0)
        with pytest.raises(ValueError):
            TenantPolicy(rate=-1.0)
        with pytest.raises(ValueError):
            TenantPolicy(priority=0.0)
        with pytest.raises(ValueError):
            TenantPolicy(budget_share=1.5)
        p = as_tenant_policy({"weight": 2.0, "rate": 5.0})
        assert p.weight == 2.0 and p.rate == 5.0
        assert as_tenant_policy(p) is p
        with pytest.raises(ValueError):
            as_tenant_policy({"wieght": 2.0})
        with pytest.raises(TypeError):
            as_tenant_policy(7)

    def test_token_bucket_injected_clock(self):
        """Refill is a pure function of the caller's clock — replayable."""
        b = TokenBucket(rate=2.0, burst=4.0, now=0.0)
        for _ in range(4):
            assert b.peek(0.0) >= 1.0
            b.take(0.0)
        assert b.peek(0.0) == 0.0
        assert b.peek(1.0) == pytest.approx(2.0)   # 1 s * 2/s
        assert b.peek(100.0) == 4.0                # capped at burst

    def test_undeclared_tenant_is_default_policy(self):
        table = TenantTable({"alice": {"weight": 3.0}}, now=0.0)
        p = table.policy("nobody")
        assert (p.weight, p.rate, p.priority, p.budget_share) == \
            (1.0, None, 1.0, None)
        assert table.has_tokens("nobody", now=0.0)  # no limit declared


# ---------------------------------------------------------------------------
# Queue-tier QoS: WFQ, EDF, budget shares, the budget-leak audit.


class TestQueueQoS:
    def test_weighted_fair_share_and_no_starvation(self):
        table = TenantTable({"alice": {"weight": 3.0},
                             "bob": {"weight": 1.0}}, now=0.0)
        q = AdmissionQueue(max_depth=80, qos=table)
        for i in range(40):
            q.admit(_mk_req(2 * i, "alice"))
            q.admit(_mk_req(2 * i + 1, "bob"))
        head = [q.pop(timeout=0.1).tenant for _ in range(40)]
        assert 27 <= head.count("alice") <= 33
        bob_at = [i for i, t in enumerate(head) if t == "bob"]
        assert max(j - i for i, j in zip(bob_at, bob_at[1:])) <= 6
        # Work conservation: the tail (one live tenant) drains fully.
        tail = [q.pop(timeout=0.1) for _ in range(40)]
        assert all(r is not None for r in tail)
        assert q.depth() == 0

    def test_single_tenant_is_plain_fifo(self):
        table = TenantTable({"alice": {"weight": 3.0}}, now=0.0)
        table.charge("alice", 100.0)     # huge virtual clock
        q = AdmissionQueue(max_depth=8, qos=table)
        for i in range(5):
            q.admit(_mk_req(i, "alice"))
        assert [q.pop(timeout=0.1).id for _ in range(5)] == \
            [f"t-{i}" for i in range(5)]

    def test_edf_ordering(self):
        q = AdmissionQueue(max_depth=8, ordering="edf")
        now = time.monotonic()
        q.admit(_mk_req(0, "d", deadline=now + 30))
        q.admit(_mk_req(1, "d", deadline=now + 10))
        q.admit(_mk_req(2, "d"))
        q.admit(_mk_req(3, "d", deadline=now + 20))
        assert [q.pop(timeout=0.1).id for _ in range(4)] == \
            ["t-1", "t-3", "t-0", "t-2"]

    def test_budget_share_caps_one_tenant_only(self):
        table = TenantTable({"mallory": {"budget_share": 0.25}}, now=0.0)
        q = AdmissionQueue(max_depth=16, max_deadline_budget_s=100.0,
                           qos=table)
        now = time.monotonic()
        q.admit(_mk_req(0, "mallory", deadline=now + 20))
        with pytest.raises(AdmissionError) as ei:
            q.admit(_mk_req(1, "mallory", deadline=now + 20))
        assert ei.value.reason is AdmissionReason.DEADLINE_BUDGET
        assert "share" in ei.value.detail
        # Another tenant still has the rest of the aggregate cap.
        q.admit(_mk_req(2, "alice", deadline=now + 20))
        assert q.depth() == 2

    def test_no_rejection_leaks_budget(self):
        """Every rejection path releases everything: no token consumed,
        no deadline budget retained, no depth change."""
        table = TenantTable({"carol": {"rate": 1.0, "burst": 2.0}},
                            now=time.monotonic())
        # QUEUE_FULL first: the queue is full before carol arrives.
        q = AdmissionQueue(max_depth=1, qos=table)
        q.admit(_mk_req(0, "filler"))
        before = q.deadline_budget()
        with pytest.raises(AdmissionError) as ei:
            q.admit(_mk_req(1, "carol",
                            deadline=time.monotonic() + 50))
        assert ei.value.reason is AdmissionReason.QUEUE_FULL
        assert q.depth() == 1 and q.deadline_budget() == before
        assert table.snapshot()["carol"]["tokens"] == 2.0
        # DEADLINE_BUDGET next: the aggregate cap rejects, token intact.
        q2 = AdmissionQueue(max_depth=8, max_deadline_budget_s=5.0,
                            qos=table)
        with pytest.raises(AdmissionError) as ei:
            q2.admit(_mk_req(2, "carol",
                             deadline=time.monotonic() + 50))
        assert ei.value.reason is AdmissionReason.DEADLINE_BUDGET
        assert q2.depth() == 0
        assert table.snapshot()["carol"]["tokens"] == 2.0
        # SHUTDOWN: a closed queue consumes nothing either.
        q3 = AdmissionQueue(max_depth=8, qos=table)
        q3.close()
        with pytest.raises(AdmissionError) as ei:
            q3.admit(_mk_req(3, "carol"))
        assert ei.value.reason is AdmissionReason.SHUTDOWN
        assert table.snapshot()["carol"]["tokens"] == 2.0
        # Tokens ARE spent on success — and run dry loudly.
        q4 = AdmissionQueue(max_depth=8, qos=table)
        q4.admit(_mk_req(4, "carol"))
        q4.admit(_mk_req(5, "carol"))
        with pytest.raises(AdmissionError) as ei:
            q4.admit(_mk_req(6, "carol"))
        assert ei.value.reason is AdmissionReason.RATE_LIMITED
        assert q4.depth() == 2   # the rejected one is not queued


# ---------------------------------------------------------------------------
# Service-tier tenancy: identity, isolation, attribution.


class TestServiceTenancy:
    def test_identity_rate_limit_and_healthz(self):
        cfg = _cfg(metrics=True,
                   tenants={"alice": {"weight": 3.0},
                            "mallory": {"rate": 0.001, "burst": 1.0}},
                   api_tokens={"tok-alice": "alice"})
        with SVDService(cfg) as svc:
            r = svc.submit(_mat(1), api_token="tok-alice").result(
                timeout=600.0)
            assert r.status.name == "OK"
            assert svc.submit(_mat(2)).result(
                timeout=600.0).status.name == "OK"   # default tenant
            assert svc.submit(_mat(3), tenant="mallory").result(
                timeout=600.0).status.name == "OK"
            with pytest.raises(AdmissionError) as ei:
                svc.submit(_mat(4), tenant="mallory")
            assert ei.value.reason is AdmissionReason.RATE_LIMITED
            with pytest.raises(AdmissionError) as ei:
                svc.submit(_mat(5), api_token="tok-stolen")
            assert ei.value.reason is AdmissionReason.UNKNOWN_TENANT
        # Post-close reads (workers joined): a ticket unblocks BEFORE
        # its finalize bookkeeping lands, so stats/records are only
        # settled once the service has stopped.
        tenants = svc.healthz()["tenants"]
        assert tenants["alice"]["stats"]["served"] == 1
        assert tenants["alice"]["qos"]["weight"] == 3.0
        assert tenants["mallory"]["stats"]["rejected:rate_limited"] == 1
        assert tenants["mallory"]["qos"]["tokens"] is not None
        assert tenants[DEFAULT_TENANT]["stats"]["served"] == 1
        # Metrics carry the tenant dimension, live.
        text = svc.metrics_text()
        assert 'tenant="mallory"' in text and 'tenant="alice"' in text

    def test_identity_faults_never_failover_or_burn(self):
        """UNKNOWN_TENANT and RATE_LIMITED are the caller's fault /
        the caller's contract — neither may trigger router failover
        (farming the ring would multiply the effective rate by the
        replica count), and only RATE_LIMITED burns error budget."""
        assert AdmissionReason.UNKNOWN_TENANT not in _FAILOVER_REASONS
        assert AdmissionReason.RATE_LIMITED not in _FAILOVER_REASONS
        cfg = _cfg(api_tokens={"tok-alice": "alice"})
        with SVDService(cfg) as svc:
            with pytest.raises(AdmissionError):
                svc.submit(_mat(6), api_token="nope")
            recs = svc.records()
        snaps = tenant_slo_from_records(recs)
        assert sum(_slo_totals(s)["shed"] for s in snaps.values()) == 0

    def test_result_cache_is_tenant_isolated(self):
        cfg = _cfg(tenants={"alice": {}, "bob": {}},
                   result_cache_bytes=16 << 20, compute_digest=True)
        a = _mat(10)
        with SVDService(cfg) as svc:
            svc.submit(a, tenant="alice").result(timeout=600.0)
            svc.submit(a, tenant="alice").result(timeout=600.0)  # hit
            svc.submit(a, tenant="bob").result(timeout=600.0)    # miss
            svc.submit(a, tenant="bob").result(timeout=600.0)    # hit
        t = svc.healthz()["tenants"]
        assert t["alice"]["stats"].get("cache_hits", 0) == 1
        assert t["bob"]["stats"].get("cache_hits", 0) == 1
        assert t["bob"]["stats"]["served"] == 2

    def test_shared_cache_opt_in(self):
        cfg = _cfg(tenants={"alice": {}, "bob": {}},
                   result_cache_bytes=16 << 20, compute_digest=True,
                   shared_result_cache=True)
        a = _mat(11)
        with SVDService(cfg) as svc:
            svc.submit(a, tenant="alice").result(timeout=600.0)
            svc.submit(a, tenant="bob").result(timeout=600.0)
        t = svc.healthz()["tenants"]
        assert t["bob"]["stats"].get("cache_hits", 0) == 1

    def test_live_vs_offline_slo_agreement(self):
        cfg = _cfg(metrics=True,
                   tenants={"alice": {"weight": 2.0},
                            "mallory": {"rate": 0.001, "burst": 1.0}})
        with SVDService(cfg) as svc:
            svc.submit(_mat(20), tenant="alice").result(timeout=600.0)
            svc.submit(_mat(21), tenant="mallory").result(timeout=600.0)
            with pytest.raises(AdmissionError):
                svc.submit(_mat(22), tenant="mallory")
        hz = svc.healthz()
        recs = svc.records()
        live = {t: _slo_totals(info["slo"])
                for t, info in hz["tenants"].items() if info.get("slo")}
        offline = {t: _slo_totals(s)
                   for t, s in tenant_slo_from_records(recs).items()}
        assert live == offline
        assert offline["mallory"]["shed"] == 1
        # And the reconstructed registry is tenant-labeled.
        snap = registry_from_manifest(recs).snapshot()
        assert all("tenant=" in lbl for lbl in
                   snap["svdj_requests_finalized_total"]["series"])
        assert any("tenant=mallory" in lbl for lbl in
                   snap["svdj_requests_rejected_total"]["series"])

    def test_manifest_tenant_roundtrip(self):
        rec = manifest.build_serve(
            request_id="mt-0", m=28, n=28, dtype="float64",
            bucket="32x32:float64", queue_wait_s=0.01, solve_time_s=0.1,
            status="OK", path="solve", breaker="CLOSED", brownout="FULL",
            tenant="alice")
        assert rec["tenant"] == "alice"
        manifest.validate(rec)           # typed-optional: str is fine
        bad = dict(rec, tenant=5)
        with pytest.raises(ValueError):
            manifest.validate(bad)
        # Pre-tenancy records reconstruct under the default tenant.
        old = {k: v for k, v in rec.items() if k != "tenant"}
        manifest.validate(old)
        snaps = tenant_slo_from_records([old, rec])
        assert set(snaps) == {"alice", DEFAULT_TENANT}

    def test_journal_attribution_survives_restart(self, tmp_path):
        """A journaled admit carries its tenant; recovery re-admits the
        debt under the ORIGINAL tenant (not the rescuer's), and a
        pre-tenancy journal record lands on the default tenant."""
        jpath = tmp_path / "journal.jsonl"
        j = Journal(jpath, exclusive=True)
        for rid, tenant, seed in (("jr-alice", "alice", 30),
                                  ("jr-old", "pre-tenancy", 31)):
            req = _mk_req(0, tenant, submitted=time.monotonic())
            req.a = _mat(seed)
            req.id = rid
            j.append_admit(req)
        j.release()
        raw = [json.loads(ln) for ln in
               jpath.read_text().splitlines() if ln.strip()]
        assert raw[0]["tenant"] == "alice"
        # Strip the second record's tenant key: the pre-tenancy stream
        # shape. Both recover side by side.
        old = {k: v for k, v in raw[1].items() if k != "tenant"}
        jpath.write_text(json.dumps(raw[0]) + "\n"
                         + json.dumps(old) + "\n")
        with SVDService(_cfg(journal_path=str(jpath))) as svc:
            tickets = svc.recover()
            assert set(tickets) == {"jr-alice", "jr-old"}
            for t in tickets.values():
                assert t.result(timeout=600.0).status.name == "OK"
        # After close (workers joined): a ticket unblocks BEFORE its
        # manifest record is appended, so read records post-shutdown.
        recs = svc.records()
        by_id = {r["request"]["id"]: r for r in recs
                 if r.get("kind") == "serve"}
        assert by_id["jr-alice"]["tenant"] == "alice"
        assert by_id["jr-old"]["tenant"] == DEFAULT_TENANT


# ---------------------------------------------------------------------------
# Adversarial-tenant fairness drills (chaos lane): the abuser is
# contained, the victim's experience is unchanged — asserted from
# validated serve records (tenant_slo_from_records), not timers.


def _run_schedule(svc, events, oracle=None):
    """Replay an adversarial_tenant schedule: submit every event in
    order (compressed time — determinism lives in the token/budget
    arithmetic, not in sleeps), collect tickets, wait for all."""
    tickets, rejected = [], []
    for ev in events:
        try:
            tickets.append(svc.submit(
                _mat(ev["mat_seed"]), tenant=ev["tenant"],
                deadline_s=ev["deadline_s"]))
        except AdmissionError as e:
            rejected.append((ev["tenant"], e.reason))
    for t in tickets:
        t.result(timeout=600.0)
    return tickets, rejected


@pytest.mark.chaos
class TestAdversarialDrill:
    def test_flood_single_host(self):
        events = chaos.adversarial_tenant("flood", n_victim=8,
                                          abuse_factor=4)
        cfg = _cfg(metrics=True, queue_ordering="edf",
                   tenants={"alice": {"weight": 4.0},
                            "mallory": {"rate": 0.5, "burst": 2.0}})
        with SVDService(cfg) as svc:
            _, rejected = _run_schedule(svc, events)
        recs = svc.records()
        assert all(t == "mallory" and r is AdmissionReason.RATE_LIMITED
                   for t, r in rejected)
        snaps = {t: _slo_totals(s)
                 for t, s in tenant_slo_from_records(recs).items()}
        # The victim's experience is untouched: every submit served OK.
        assert snaps["alice"]["ok"] == 8 and snaps["alice"]["shed"] == 0
        # The flood is contained: ~burst admits, the rest shed loudly.
        assert snaps["mallory"]["shed"] >= 25
        assert snaps["mallory"]["served"] <= 7

    def test_deadline_abuse_single_host(self):
        events = chaos.adversarial_tenant("deadline_abuse", n_victim=6,
                                          abuse_factor=4)
        cfg = _cfg(metrics=True, max_deadline_budget_s=120.0,
                   tenants={"alice": {"weight": 4.0},
                            "mallory": {"budget_share": 0.1}})
        with SVDService(cfg) as svc:
            # Victim deadlines are generous-but-finite; the abuser's
            # 3600 s promises blow its 10% share immediately.
            for ev in events:
                ev = dict(ev, deadline_s=(
                    60.0 if ev["tenant"] == "alice" else ev["deadline_s"]))
                try:
                    svc.submit(_mat(ev["mat_seed"]), tenant=ev["tenant"],
                               deadline_s=ev["deadline_s"]).result(
                        timeout=600.0)
                except AdmissionError as e:
                    assert ev["tenant"] == "mallory"
                    assert e.reason is AdmissionReason.DEADLINE_BUDGET
        recs = svc.records()
        snaps = {t: _slo_totals(s)
                 for t, s in tenant_slo_from_records(recs).items()}
        assert snaps["alice"]["ok"] == 6
        assert snaps["mallory"]["shed"] >= 1

    def test_flood_through_http_router(self, tmp_path):
        """The same fairness contract through the wire: tenant identity
        crosses the HTTP transport, the receiving replica's QoS rejects
        the flood, and RATE_LIMITED never farms the ring (no failover)."""
        cfg = _cfg(metrics=True,
                   tenants={"alice": {"weight": 4.0},
                            "mallory": {"rate": 0.5, "burst": 2.0}},
                   api_tokens={"tok-alice": "alice"},
                   journal_path=str(tmp_path / "journal-0.jsonl"))
        server = HttpReplicaServer(cfg).start()
        router = None
        try:
            handle = HttpReplica(0, server.address,
                                 tmp_path / "journal-0.jsonl")
            rcfg = RouterConfig(
                replicas=1, serve=_cfg(),
                state_dir=str(tmp_path / "router-state"),
                supervise_interval_s=0.05)
            router = ReplicaRouter(rcfg, replicas=[handle]).start()
            events = chaos.adversarial_tenant("flood", n_victim=4,
                                              abuse_factor=4)
            # One token-identified submit first: the ROUTER cannot
            # resolve tokens (the map lives in the replica's config) —
            # the receiving replica must attribute it to alice anyway.
            tickets = [router.submit(np.asarray(_mat(99)),
                                     deadline_s=600.0,
                                     api_token="tok-alice")]
            rejected = []
            for ev in events:
                try:
                    tickets.append(router.submit(
                        np.asarray(_mat(ev["mat_seed"])),
                        deadline_s=600.0, tenant=ev["tenant"]))
                except AdmissionError as e:
                    rejected.append(e.reason)
            for t in tickets:
                res = t.result(timeout=600.0)
                assert res.error is None and res.status.name == "OK"
            assert rejected and all(
                r is AdmissionReason.RATE_LIMITED for r in rejected)
        finally:
            if router is not None:
                router.stop()
            server.stop(drain=True, timeout=30.0)
        # Post-shutdown (settled records): attribution survived the
        # wire — the REPLICA's records reconstruct per-tenant truth
        # (token-resolved alice too).
        snaps = {t: _slo_totals(s) for t, s in
                 tenant_slo_from_records(server.svc.records()).items()}
        assert snaps["alice"]["ok"] == 5   # 4 explicit + 1 by token
        assert snaps["mallory"]["shed"] == len(rejected)
        # The router's own route records carry the tenant label.
        routes = [r for r in router.records()
                  if r.get("event") == "route"]
        assert {r.get("tenant") for r in routes} >= {"alice", "mallory"}
