"""Differentiable-solver lane: the custom VJP/JVP rules of
`svd_jacobi_tpu.grad` attached to `solver.svd` / `svd_topk` / `svd_tall`.

Covers the contracts README "Differentiable solves" documents:
VJP/JVP against f64 central finite differences and against
`jnp.linalg.svd`'s own rule on gap/flat/clustered spectra, the
degenerate-sigma no-NaN guarantee (masked F-matrix), the sigma-only
fast-path equivalence, jit/vmap/scan composition, grad-under-chaos
(NaN cotangent -> finite gradient), the loud uncovered-path errors, the
`grad_degenerate_rtol` knob resolution, and the GRAD001 analysis pass
with its seeded failing fixtures.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from svd_jacobi_tpu import solver
from svd_jacobi_tpu.config import SVDConfig
from svd_jacobi_tpu.grad import (NonDifferentiableError, degenerate_mask,
                                 fmatrix, sigma_recip)

pytestmark = pytest.mark.grad

VJP_CFG = SVDConfig(grad_rule="vjp")


def _make_matrix(m, n, sigmas, seed=0, dtype=jnp.float32):
    """U diag(sigmas) V^T with random orthonormal factors (f64 build,
    cast at the end) — a matrix whose spectrum the test controls."""
    rng = np.random.default_rng(seed)
    qu, _ = np.linalg.qr(rng.standard_normal((m, min(m, n))))
    qv, _ = np.linalg.qr(rng.standard_normal((n, min(m, n))))
    s = np.zeros(min(m, n))
    s[:len(sigmas)] = sigmas
    return jnp.asarray(qu @ np.diag(s) @ qv.T, dtype)


def _gap_matrix(m=48, n=32, seed=0, dtype=jnp.float32):
    sig = 2.0 ** (-np.arange(min(m, n), dtype=np.float64) / 4.0)
    return _make_matrix(m, n, sig, seed=seed, dtype=dtype)


def _fd_directional(np_loss, a, d, h=1e-4):
    """f64 central finite difference of a host-side loss along d."""
    a64 = np.asarray(a, np.float64)
    d64 = np.asarray(d, np.float64)
    return (np_loss(a64 + h * d64) - np_loss(a64 - h * d64)) / (2 * h)


def _np_nuclear(x):
    return float(np.linalg.svd(x, compute_uv=False).sum())


def _directions(shape, k=3, seed=7):
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(k):
        d = rng.standard_normal(shape)
        out.append(jnp.asarray(d / np.linalg.norm(d), jnp.float32))
    return out


def _nuclear(config=None, **kw):
    def loss(a):
        return jnp.sum(solver.svd(a, config=config, **kw).s)
    return loss


class TestEconomyRule:
    def test_nuclear_grad_matches_fd(self):
        a = _gap_matrix()
        g = jax.grad(_nuclear())(a)
        assert np.isfinite(np.asarray(g)).all()
        for d in _directions(a.shape):
            got = float(jnp.vdot(g, d))
            want = _fd_directional(_np_nuclear, a, d)
            assert got == pytest.approx(want, rel=2e-3, abs=1e-4)

    def test_jvp_matches_fd(self):
        a = _gap_matrix(seed=1)
        for d in _directions(a.shape, k=2):
            _, tang = jax.jvp(_nuclear(), (a,), (d,))
            want = _fd_directional(_np_nuclear, a, d)
            assert float(tang) == pytest.approx(want, rel=2e-3, abs=1e-4)

    def test_nuclear_grad_matches_jnp_rule(self):
        a = _gap_matrix(seed=2)
        ours = jax.grad(_nuclear())(a)
        ref = jax.grad(
            lambda x: jnp.sum(jnp.linalg.svd(x, compute_uv=False)))(a)
        np.testing.assert_allclose(np.asarray(ours), np.asarray(ref),
                                   rtol=1e-3, atol=1e-4)

    def test_subspace_loss_grad_f64_matches_fd(self):
        # A loss through the VECTORS (top-2 left projector): exercises
        # the F-matrix terms, which the nuclear norm never touches. The
        # f64 qr-svd lane gives the tight comparison.
        a = _gap_matrix(32, 24, seed=3, dtype=jnp.float64)
        rng = np.random.default_rng(11)
        c = jnp.asarray(rng.standard_normal((32, 32)), jnp.float64)

        def loss(x):
            u = solver.svd(x).u[:, :2]
            return jnp.sum((u @ u.T) * c)

        def np_loss(x):
            u = np.linalg.svd(x)[0][:, :2]
            return float(np.sum((u @ u.T) * np.asarray(c)))

        g = jax.grad(loss)(a)
        for d in _directions(a.shape, k=2):
            got = float(jnp.vdot(g, d.astype(jnp.float64)))
            want = _fd_directional(np_loss, a, d, h=1e-6)
            assert got == pytest.approx(want, rel=1e-5, abs=1e-8)

    def test_vjp_mode_matches_jvp_mode(self):
        # The explicit custom_vjp cotangent formula IS the transpose of
        # the custom_jvp rule: same factors in, (near-)identical
        # gradients out — through a vector-touching loss so the F-matrix
        # and null-space terms are both exercised.
        a = _gap_matrix(seed=4)
        rng = np.random.default_rng(5)
        c = jnp.asarray(rng.standard_normal(a.shape), jnp.float32)

        def loss(cfg):
            def f(x):
                r = solver.svd(x, config=cfg)
                return jnp.sum(r.u * c) + jnp.sum(r.s ** 2)
            return f

        g_jvp = jax.grad(loss(None))(a)
        g_vjp = jax.grad(loss(VJP_CFG))(a)
        # Same factors, same masked terms; the only daylight is f32
        # rounding between the two operation orders.
        scale = float(jnp.abs(g_jvp).max())
        np.testing.assert_allclose(np.asarray(g_jvp) / scale,
                                   np.asarray(g_vjp) / scale,
                                   rtol=1e-4, atol=1e-5)

    def test_wide_input_grad(self):
        # m < n transposes internally; the rule rides the recursion.
        a = _gap_matrix(32, 48, seed=6)
        g = jax.grad(_nuclear())(a)
        assert g.shape == a.shape
        r = solver.svd(a)
        np.testing.assert_allclose(np.asarray(g),
                                   np.asarray(r.u @ r.v.T),
                                   rtol=1e-4, atol=1e-5)


class TestDegenerateSigma:
    def test_repeated_sigma_no_nan(self):
        # Exact ties and near-zero sigmas: every F-matrix denominator is
        # degenerate somewhere — the masked rule must stay finite in
        # both modes and both AD directions.
        a = _make_matrix(40, 24, [3.0, 3.0, 2.0, 2.0, 1.0] + [1e-9] * 19,
                         seed=8)
        rng = np.random.default_rng(9)
        c = jnp.asarray(rng.standard_normal((40, 24)), jnp.float32)

        def loss(cfg):
            def f(x):
                r = solver.svd(x, config=cfg)
                return jnp.sum(r.u * c) + jnp.sum(r.s)
            return f

        for cfg in (None, VJP_CFG):
            g = jax.grad(loss(cfg))(a)
            assert np.isfinite(np.asarray(g)).all(), cfg
        _, tang = jax.jvp(loss(None), (a,), (jnp.ones_like(a),))
        assert np.isfinite(float(tang))

    def test_clustered_nuclear_grad_still_matches_fd(self):
        # A clustered spectrum masks the intra-cluster F terms, but the
        # nuclear norm is cluster-invariant — its gradient (U V^T) stays
        # exact through the mask.
        sig = np.concatenate([np.full(4, 1.0 + 1e-8), np.full(4, 0.5),
                              2.0 ** (-np.arange(16) / 2.0 - 2)])
        a = _make_matrix(48, 24, sig, seed=10)
        g = jax.grad(_nuclear())(a)
        assert np.isfinite(np.asarray(g)).all()
        for d in _directions(a.shape, k=2):
            got = float(jnp.vdot(g, d))
            want = _fd_directional(_np_nuclear, a, d)
            assert got == pytest.approx(want, rel=2e-3, abs=1e-4)

    def test_zero_matrix_finite(self):
        a = jnp.zeros((24, 16), jnp.float32)
        for cfg in (None, VJP_CFG):
            g = jax.grad(_nuclear(cfg))(a)
            assert np.isfinite(np.asarray(g)).all()

    def test_fmatrix_helpers_finite_and_masked(self):
        s = jnp.asarray([2.0, 2.0, 1.0, 0.0], jnp.float32)
        f = fmatrix(s, 1e-6)
        assert np.isfinite(np.asarray(f)).all()
        m = np.asarray(degenerate_mask(s, 1e-6))
        assert not m[0, 1] and not m[1, 0]      # the tie is masked
        assert m[0, 2] and m[2, 3]              # clear gaps are not
        assert not np.asarray(m.diagonal()).any()
        r = np.asarray(sigma_recip(s, 1e-6))
        assert np.isfinite(r).all() and r[3] == 0.0


class TestSigmaOnly:
    def test_sigma_only_equals_full_gradient(self):
        a = _gap_matrix(seed=12)
        g_full = jax.grad(_nuclear())(a)
        g_sig = jax.grad(_nuclear(compute_u=False, compute_v=False))(a)
        np.testing.assert_allclose(np.asarray(g_sig), np.asarray(g_full),
                                   rtol=1e-4, atol=1e-5)

    def test_sigma_only_vjp_mode(self):
        a = _gap_matrix(seed=13)
        g = jax.grad(_nuclear(VJP_CFG, compute_u=False,
                              compute_v=False))(a)
        r = solver.svd(a)
        np.testing.assert_allclose(np.asarray(g),
                                   np.asarray(r.u @ r.v.T),
                                   rtol=1e-4, atol=1e-5)

    def test_one_factor_requested(self):
        a = _gap_matrix(seed=14)
        g = jax.grad(lambda x: jnp.sum(
            solver.svd(x, compute_v=False).s))(a)
        assert np.isfinite(np.asarray(g)).all()


class TestComposition:
    def test_jit_grad(self):
        a = _gap_matrix(seed=15)
        eager = jax.grad(_nuclear())(a)
        jitted = jax.jit(jax.grad(_nuclear()))(a)
        np.testing.assert_allclose(np.asarray(jitted), np.asarray(eager),
                                   rtol=1e-5, atol=1e-6)

    def test_vmap_grad(self):
        stack = jnp.stack([_gap_matrix(32, 24, seed=s) for s in (1, 2, 3)])
        gb = jax.vmap(jax.grad(_nuclear()))(stack)
        assert gb.shape == stack.shape
        g0 = jax.grad(_nuclear())(stack[0])
        np.testing.assert_allclose(np.asarray(gb[0]), np.asarray(g0),
                                   rtol=1e-5, atol=1e-6)

    def test_scan_grad(self):
        a = _gap_matrix(32, 24, seed=16)

        def loss(x):
            def body(c, _):
                return c * 0.5, _nuclear()(x * c)
            _, ys = jax.lax.scan(body, jnp.float32(1.0), None, length=2)
            return jnp.sum(ys)

        g = jax.grad(loss)(a)
        # sum_i c_i * ||a||_* gradient = (1 + 0.5) * U V^T
        r = solver.svd(a)
        np.testing.assert_allclose(np.asarray(g),
                                   1.5 * np.asarray(r.u @ r.v.T),
                                   rtol=1e-4, atol=1e-5)

    def test_warmstart_grad_matches_cold(self):
        a = _gap_matrix(seed=17)
        prior = solver.svd(a)
        a2 = a + 1e-3 * jnp.outer(jnp.ones(a.shape[0]),
                                  jnp.ones(a.shape[1])) / a.shape[0]
        g_cold = jax.grad(_nuclear())(a2)
        g_warm = jax.grad(lambda x: jnp.sum(
            solver.svd(x, v0=prior.v).s))(a2)
        np.testing.assert_allclose(np.asarray(g_warm), np.asarray(g_cold),
                                   rtol=1e-3, atol=1e-4)


class TestLaneRules:
    def test_topk_grad_matches_truncated_full(self):
        a = _gap_matrix(64, 48, seed=18)
        k = 6
        g_topk = jax.grad(lambda x: jnp.sum(solver.svd_topk(x, k).s))(a)
        g_full = jax.grad(lambda x: jnp.sum(solver.svd(x).s[:k]))(a)
        assert np.isfinite(np.asarray(g_topk)).all()
        np.testing.assert_allclose(np.asarray(g_topk), np.asarray(g_full),
                                   rtol=5e-3, atol=1e-3)

    def test_topk_sigma_only_and_vjp_mode(self):
        a = _gap_matrix(64, 48, seed=19)
        g1 = jax.grad(lambda x: jnp.sum(
            solver.svd_topk(x, 6, compute_u=False,
                            compute_v=False).s))(a)
        g2 = jax.grad(lambda x: jnp.sum(
            solver.svd_topk(x, 6, config=VJP_CFG).s))(a)
        np.testing.assert_allclose(np.asarray(g1), np.asarray(g2),
                                   rtol=1e-3, atol=1e-4)

    def test_tall_grad_matches_fd(self):
        sig = 2.0 ** (-np.arange(12, dtype=np.float64) / 3.0)
        a = _make_matrix(160, 12, sig, seed=20)
        g = jax.grad(lambda x: jnp.sum(solver.svd_tall(x).s))(a)
        assert np.isfinite(np.asarray(g)).all()
        for d in _directions(a.shape, k=2):
            got = float(jnp.vdot(g, d))
            want = _fd_directional(_np_nuclear, a, d)
            assert got == pytest.approx(want, rel=2e-3, abs=1e-4)


class TestChaosGuard:
    def test_nan_cotangent_finite_vjp_mode(self):
        # grad-under-chaos: a fully-poisoned sigma cotangent is zeroed
        # by the custom_vjp chaos guard — the pullback stays finite
        # (exactly zero: the loud sentinel), and the forward solve's
        # health word is untouched (OK).
        a = _gap_matrix(seed=21)
        f = lambda x: solver.svd(x, config=VJP_CFG)
        r, pullback = jax.vjp(lambda x: f(x).s, a)
        (abar,) = pullback(jnp.full_like(r, jnp.nan))
        assert np.isfinite(np.asarray(abar)).all()
        assert float(jnp.abs(abar).max()) == 0.0
        assert f(a).status_enum() == solver.SolveStatus.OK

    def test_partial_nan_cotangent_keeps_finite_entries(self):
        a = _gap_matrix(seed=22)
        s, pullback = jax.vjp(
            lambda x: solver.svd(x, config=VJP_CFG).s, a)
        ct = jnp.zeros_like(s).at[0].set(jnp.nan).at[1].set(1.0)
        (abar,) = pullback(ct)
        assert np.isfinite(np.asarray(abar)).all()
        # The finite entry's contribution survives: u_1 v_1^T.
        r = solver.svd(a)
        want = np.outer(np.asarray(r.u)[:, 1], np.asarray(r.v)[:, 1])
        np.testing.assert_allclose(np.asarray(abar), want,
                                   rtol=1e-4, atol=1e-5)


class TestUncoveredPaths:
    def test_full_matrices_raises_clearly(self):
        a = _gap_matrix(seed=23)
        with pytest.raises(NonDifferentiableError,
                           match="full_matrices=False"):
            jax.grad(lambda x: jnp.sum(
                solver.svd(x, full_matrices=True).s))(a)
        # The plain forward call is unchanged.
        assert solver.svd(a, full_matrices=True).u.shape == (48, 48)

    def test_square_full_matrices_still_differentiable(self):
        # m == n: the economy U IS the full U — no completion, rule on.
        a = _gap_matrix(24, 24, seed=24)
        g = jax.grad(lambda x: jnp.sum(
            solver.svd(x, full_matrices=True).s))(a)
        assert np.isfinite(np.asarray(g)).all()

    def test_batched_raises_naming_vmap(self):
        stack = jnp.stack([_gap_matrix(24, 16, seed=s) for s in (1, 2)])
        with pytest.raises(NonDifferentiableError, match="vmap"):
            jax.grad(lambda x: jnp.sum(solver.svd_batched(x).s))(stack)
        assert solver.svd_batched(stack).s.shape == (2, 16)

    def test_sharded_raises_naming_alternative(self):
        from svd_jacobi_tpu.parallel import sharded
        a = _gap_matrix(64, 48, seed=28)
        with pytest.raises(NonDifferentiableError, match="solver.svd"):
            jax.grad(lambda x: jnp.sum(sharded.svd(x).s))(a)
        assert sharded.svd(a).s.shape == (48,)

    def test_resilient_svd_raises_naming_alternative(self):
        from svd_jacobi_tpu.resilience import resilient_svd
        a = _gap_matrix(seed=25)
        with pytest.raises(NonDifferentiableError, match="solver.svd"):
            jax.grad(lambda x: jnp.sum(resilient_svd(x).s))(a)

    def test_jvp_through_vjp_mode_raises_jax_error(self):
        a = _gap_matrix(seed=26)
        with pytest.raises(TypeError, match="custom_vjp"):
            jax.jvp(_nuclear(VJP_CFG), (a,), (jnp.ones_like(a),))

    def test_unknown_grad_rule_rejected(self):
        a = _gap_matrix(seed=27)
        with pytest.raises(ValueError, match="grad_rule"):
            solver.svd(a, config=SVDConfig(grad_rule="bogus"))


class TestKnobResolution:
    def test_table_rows_resolve_per_dtype(self):
        # The shipped per-dtype rows: f32's cluster band is ~1e9x wider
        # than f64's (matching each dtype's sigma^2 solve noise).
        f32 = solver._resolve_grad_rtol(SVDConfig(), 1024, 1024,
                                        jnp.float32)
        f64 = solver._resolve_grad_rtol(SVDConfig(), 1024, 1024,
                                        jnp.float64)
        assert f32 == pytest.approx(1e-6)
        assert f64 == pytest.approx(2e-15)
        assert f32 > 1e6 * f64

    def test_explicit_knob_wins_and_validates(self):
        cfg = SVDConfig(grad_degenerate_rtol=3e-4)
        assert solver._resolve_grad_rtol(cfg, 64, 64,
                                         jnp.float32) == pytest.approx(3e-4)
        with pytest.raises(ValueError, match="grad_degenerate_rtol"):
            solver._resolve_grad_rtol(
                SVDConfig(grad_degenerate_rtol=-1.0), 64, 64, jnp.float32)

    def test_dtype_floor_fallback(self):
        # With tables bypassed, the band falls back to 8*eps of the
        # accumulation dtype.
        from svd_jacobi_tpu.tune import tables
        tables.set_active_table("off")
        try:
            got = solver._resolve_grad_rtol(SVDConfig(), 64, 64,
                                            jnp.float32)
            assert got == pytest.approx(
                8 * float(jnp.finfo(jnp.float32).eps))
        finally:
            tables.set_active_table(None)

    def test_resolve_config_pins_grad_band(self):
        from svd_jacobi_tpu.tune import tables
        cfg = tables.resolve_config(SVDConfig(), 96, 64, "float32",
                                    backend="cpu", device_kind="x")
        assert cfg.grad_degenerate_rtol == pytest.approx(1e-6)
        pinned = dataclasses.replace(SVDConfig(),
                                     grad_degenerate_rtol=7e-5)
        cfg2 = tables.resolve_config(pinned, 96, 64, "float32",
                                     backend="cpu", device_kind="x")
        assert cfg2.grad_degenerate_rtol == pytest.approx(7e-5)


class TestGrad001:
    def test_all_probes_clean(self):
        from svd_jacobi_tpu.analysis import grad_checks
        findings, report = grad_checks.run_all()
        assert findings == []
        assert any("svd.nuclear" in p for p in report["probes"])
        assert "grad._svd_vjp_jit" in report["grad_entries"]

    def test_silent_fallback_fixture_fires(self):
        from fixtures.grad_fixtures import silent_fallback_loss
        from svd_jacobi_tpu.analysis import grad_checks
        findings = grad_checks.check_grad_trace(
            silent_fallback_loss, shape=(96, 64), dtype="float32",
            where="fixture.silent_fallback")
        codes = [f.message for f in findings]
        assert any("silent fallback" in m for m in codes)
        assert any("sweep machinery" in m for m in codes)

    def test_unbudgeted_grad_jit_fixture_fires(self):
        from fixtures.grad_fixtures import unbudgeted_grad_budgets
        from svd_jacobi_tpu.analysis import grad_checks
        findings = grad_checks.check_budget_coverage(
            unbudgeted_grad_budgets())
        assert len(findings) == 1
        assert "grad._svd_vjp_jit" in findings[0].where

    def test_registry_budget_ledger_two_way(self):
        # The grad jits ride the same AOT001 two-way ledger as every
        # serving entry.
        from svd_jacobi_tpu.analysis import aot_checks
        assert aot_checks.check_budget_coverage() == []
