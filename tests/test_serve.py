"""Serving layer (`svd_jacobi_tpu.serve`): admission control, shape
buckets, deadlines/cancellation, circuit breaker + brownout, "serve"
manifest records, and the threaded soak lane.

All CPU, all threads — no TPU required. Most tests share one f64 bucket
set (`BUCKETS`) and solver config so the stepper jit entries compile once
for the whole module (which is itself the serving claim under test).
"""

import threading
import time

import numpy as np
import jax.numpy as jnp
import pytest

from svd_jacobi_tpu import SVDConfig
from svd_jacobi_tpu.obs import manifest
from svd_jacobi_tpu.resilience import chaos
from svd_jacobi_tpu.serve import (AdmissionError, AdmissionQueue,
                                  AdmissionReason, Bucket, BucketSet,
                                  BreakerState, Brownout, CircuitBreaker,
                                  ServeConfig, SVDService, as_bucket)
from svd_jacobi_tpu.solver import SolveStatus, SweepStepper
from svd_jacobi_tpu.utils import matgen

pytestmark = pytest.mark.serve

BUCKETS = ((32, 32, "float64"), (48, 32, "float64"))
SOLVER = SVDConfig(block_size=4)


def _cfg(**over):
    base = dict(buckets=BUCKETS, solver=SOLVER, max_queue_depth=8)
    base.update(over)
    return ServeConfig(**base)


def _mat(m, n, seed):
    return matgen.random_dense(m, n, seed=seed, dtype=jnp.float64)


def _sref(a):
    return np.linalg.svd(np.asarray(a, np.float64), compute_uv=False)


class TestBuckets:
    def test_as_bucket_forms(self):
        assert as_bucket((64, 48, "float32")) == Bucket(64, 48, "float32")
        assert as_bucket("64x48:float32") == Bucket(64, 48, "float32")
        assert as_bucket(Bucket(8, 8, "float64")).name == "8x8:float64"

    def test_invalid_specs(self):
        with pytest.raises(ValueError, match="MxN:dtype"):
            as_bucket("64-48-float32")
        with pytest.raises(ValueError, match="m >= n"):
            as_bucket((48, 64, "float32"))  # wide buckets are rejected
        with pytest.raises(ValueError, match="empty"):
            BucketSet(())
        with pytest.raises(ValueError, match="duplicate"):
            BucketSet(((8, 8, "float32"), "8x8:float32"))

    def test_route_cheapest_and_dtype(self):
        bs = BucketSet(((128, 32, "float32"), (64, 64, "float32"),
                        (64, 64, "float64")))
        # Tall-skinny request: the (128, 32) bucket is cheaper (m n^2)
        # than the square one even though its area is larger.
        assert bs.route(100, 20, "float32") == Bucket(128, 32, "float32")
        assert bs.route(60, 60, "float32") == Bucket(64, 64, "float32")
        assert bs.route(60, 60, "float64") == Bucket(64, 64, "float64")
        assert bs.route(200, 200, "float32") is None      # nothing fits
        assert bs.route(60, 60, "bfloat16") is None       # dtype mismatch

    def test_pad_shape(self):
        b = Bucket(8, 6, "float64")
        a = jnp.ones((5, 4), jnp.float64)
        p = BucketSet.pad(a, b)
        assert p.shape == (8, 6)
        assert float(jnp.sum(p)) == 20.0  # zero padding, data untouched


class TestAdmissionQueue:
    def _req(self, deadline=None, now=0.0):
        from svd_jacobi_tpu.serve.queue import Request
        return Request(id="x", a=None, m=4, n=4, orig_shape=(4, 4),
                       transposed=False, bucket=Bucket(4, 4, "float64"),
                       compute_u=True, compute_v=True, degraded=False,
                       deadline=deadline, deadline_s=None, submitted=now)

    def test_fifo_and_depth(self):
        q = AdmissionQueue(max_depth=2)
        q.admit(self._req())
        assert q.depth() == 1
        assert q.pop(0.01).id == "x"
        assert q.pop(0.01) is None

    def test_queue_full_rejects_loudly(self):
        q = AdmissionQueue(max_depth=2)
        q.admit(self._req())
        q.admit(self._req())
        with pytest.raises(AdmissionError) as ei:
            q.admit(self._req())
        assert ei.value.reason is AdmissionReason.QUEUE_FULL

    def test_deadline_budget_rejects(self):
        q = AdmissionQueue(max_depth=8, max_deadline_budget_s=1.0)
        now = time.monotonic()
        q.admit(self._req(deadline=now + 0.6))
        with pytest.raises(AdmissionError) as ei:
            q.admit(self._req(deadline=now + 0.6))
        assert ei.value.reason is AdmissionReason.DEADLINE_BUDGET
        # Requests without a deadline don't consume budget.
        q.admit(self._req())
        assert q.depth() == 2


class TestBreaker:
    def test_state_machine(self):
        br = CircuitBreaker(failure_threshold=2)
        assert br.begin() == ("base", BreakerState.CLOSED)
        assert br.record(False) is BreakerState.CLOSED
        assert br.record(True) is BreakerState.CLOSED    # streak resets
        br.record(False)
        assert br.record(False) is BreakerState.OPEN     # threshold hit
        assert br.begin()[0] == "ladder"
        assert br.record(False) is BreakerState.OPEN     # ladder failed
        assert br.record(True) is BreakerState.HALF_OPEN  # ladder healed
        assert br.begin()[0] == "base"                   # probe
        assert br.record(False) is BreakerState.OPEN     # probe failed
        br.record(True)
        assert br.record(True) is BreakerState.CLOSED    # probe succeeded
        assert ("closed", "open", "2 consecutive failures") \
            in br.transitions

    def test_threshold_validation(self):
        with pytest.raises(ValueError, match="failure_threshold"):
            CircuitBreaker(failure_threshold=0)


class TestStepperControl:
    """The cooperative deadline/cancel hooks on the host-stepped solver
    (the mechanism the service builds on), exercised without a service."""

    def test_deadline_before_first_sweep(self):
        a = _mat(24, 24, seed=30)
        st = SweepStepper(a, config=SOLVER)
        st.set_control(deadline=time.monotonic() - 1.0)
        state = st.init()
        assert not st.should_continue(state)
        r = st.finish(state)
        assert r.status_enum() is SolveStatus.DEADLINE
        assert int(r.sweeps) == 0

    def test_deadline_mid_solve_partial(self):
        a = _mat(32, 32, seed=31)
        st = SweepStepper(a, config=SOLVER)
        state = st.init()
        state = st.step(state)  # one sweep, then the deadline "expires"
        st.set_control(deadline=time.monotonic() - 1.0)
        assert not st.should_continue(state)
        r = st.finish(state)
        assert r.status_enum() is SolveStatus.DEADLINE
        assert int(r.sweeps) == 1
        # Loud PARTIAL result: factors exist and are finite.
        assert np.isfinite(np.asarray(r.s)).all()

    def test_cancel_wins_over_deadline(self):
        a = _mat(24, 24, seed=32)
        st = SweepStepper(a, config=SOLVER)
        st.set_control(deadline=time.monotonic() - 1.0,
                       should_cancel=lambda: True)
        state = st.init()
        assert not st.should_continue(state)
        assert st.finish(state).status_enum() is SolveStatus.CANCELLED

    def test_tolerance_wins_over_deadline(self):
        """A solve that reached its final tolerance before the control
        fired is OK, not DEADLINE — matching the decode policy for
        max_sweeps (tolerance wins over budget exhaustion)."""
        a = _mat(24, 24, seed=34)
        st = SweepStepper(a, config=SOLVER)
        state = st.init()
        while st.should_continue(state):
            state = st.step(state)
        assert st.finish(state).status_enum() is SolveStatus.OK
        # Re-evaluate the FINISHED (converged) state with an expired
        # deadline installed: still OK.
        st2 = SweepStepper(a, config=SOLVER)
        st2.set_control(deadline=time.monotonic() - 1.0)
        assert not st2.should_continue(state)
        assert st2.finish(state).status_enum() is SolveStatus.OK

    def test_control_clear(self):
        a = _mat(24, 24, seed=33)
        st = SweepStepper(a, config=SOLVER)
        st.set_control(deadline=time.monotonic() - 1.0)
        st.set_control(deadline=None)
        state = st.init()
        while st.should_continue(state):
            state = st.step(state)
        assert st.finish(state).status_enum() is SolveStatus.OK


class TestServiceBasics:
    def test_padded_buckets_match_oracle(self):
        """Requests of assorted shapes (exact-fit, strictly smaller, wide)
        pad to buckets and come back with ORIGINAL-shape factors matching
        the host oracle — padding is exact, not approximate."""
        with SVDService(_cfg()) as svc:
            cases = [(32, 32, 40), (28, 20, 41), (20, 30, 42), (48, 31, 43)]
            tickets = [(m, n, svc.submit(_mat(m, n, seed=s)))
                       for m, n, s in cases]
            for m, n, t in tickets:
                res = t.result(timeout=180.0)
                assert res.status is SolveStatus.OK, res
                k = min(m, n)
                assert res.u.shape == (m, k) and res.v.shape == (n, k)
                a = _mat(m, n, seed=dict(
                    (c[:2], c[2]) for c in cases)[(m, n)])
                np.testing.assert_allclose(np.asarray(res.s), _sref(a),
                                           rtol=1e-10, atol=1e-12)
                rec = (np.asarray(res.u) * np.asarray(res.s)[None, :]
                       @ np.asarray(res.v).T)
                assert (np.linalg.norm(rec - np.asarray(a))
                        / np.linalg.norm(np.asarray(a))) < 1e-13

    def test_sigma_only_request(self):
        with SVDService(_cfg()) as svc:
            res = svc.submit(_mat(24, 24, seed=44), compute_u=False,
                             compute_v=False).result(timeout=120.0)
        assert res.status is SolveStatus.OK
        assert res.u is None and res.v is None
        np.testing.assert_allclose(np.asarray(res.s),
                                   _sref(_mat(24, 24, seed=44)),
                                   rtol=1e-10, atol=1e-12)

    def test_no_bucket_rejection(self):
        with SVDService(_cfg()) as svc:
            with pytest.raises(AdmissionError) as ei:
                svc.submit(_mat(64, 64, seed=45))
            assert ei.value.reason is AdmissionReason.NO_BUCKET
            # f32 input, f64 buckets: dtype must match exactly.
            with pytest.raises(AdmissionError) as ei2:
                svc.submit(matgen.random_dense(16, 16, seed=46,
                                               dtype=jnp.float32))
            assert ei2.value.reason is AdmissionReason.NO_BUCKET
            recs = svc.records()
        assert [r["status"] for r in recs] == ["REJECTED_NO_BUCKET"] * 2
        assert all(r["bucket"] is None and r["path"] == "rejected"
                   for r in recs)

    def test_nonfinite_input_rejected_at_admission(self):
        """NaN input is screened at the door (resilience.guard policy):
        loud rejection, no solve spent, breaker untouched — one buggy
        client cannot trip the breaker for everyone."""
        with SVDService(_cfg()) as svc:
            bad = np.zeros((16, 16))
            bad[3, 4] = np.nan
            for _ in range(3):   # > breaker_threshold
                with pytest.raises(AdmissionError) as ei:
                    svc.submit(jnp.asarray(bad, jnp.float64))
                assert (ei.value.reason
                        is AdmissionReason.NONFINITE_INPUT)
            assert svc.breaker.state() is BreakerState.CLOSED
            rec = svc.records()[-1]
        assert rec["status"] == "REJECTED_NONFINITE_INPUT"

    def test_submit_after_stop_rejected(self):
        svc = SVDService(_cfg()).start()
        svc.stop()
        with pytest.raises(AdmissionError) as ei:
            svc.submit(_mat(16, 16, seed=47))
        assert ei.value.reason is AdmissionReason.SHUTDOWN
        # A stopped service is single-use, loudly (its queue is closed;
        # silently restarting would strand the closed-queue contract).
        with pytest.raises(RuntimeError, match="not restartable"):
            svc.start()

    def test_stop_race_admission_is_loud(self):
        """The submit-vs-stop race: admission is atomic with queue
        closure, so a submit racing stop() either lands in the queue
        (and is finalized/served) or raises SHUTDOWN — it can never
        return a ticket that silently never becomes terminal."""
        svc = SVDService(_cfg()).start()
        outcomes = []

        def hammer():
            for i in range(50):
                try:
                    outcomes.append(svc.submit(_mat(8, 8, seed=500 + i),
                                               compute_u=False,
                                               compute_v=False))
                except AdmissionError as e:
                    outcomes.append(e.reason)
        th = threading.Thread(target=hammer)
        th.start()
        time.sleep(0.05)
        svc.stop(drain=False, timeout=60.0)
        th.join(timeout=60.0)
        assert not th.is_alive()
        for o in outcomes:
            if isinstance(o, AdmissionReason):
                continue
            # Every ticket handed out MUST reach a terminal state.
            res = o.result(timeout=30.0)
            assert res.status is not None or res.error is not None

    def test_health_probes(self):
        svc = SVDService(_cfg())
        assert not svc.ready()
        svc.start()
        try:
            assert svc.ready()
            h = svc.healthz()
            assert h["ok"] and h["ready"]
            assert h["breaker"] == "closed" and h["brownout"] == "FULL"
            assert h["queue_depth"] == 0
        finally:
            svc.stop()
        assert not svc.ready()
        assert svc.healthz()["ok"] is False

    def test_stop_without_drain_cancels_queued(self):
        svc = SVDService(_cfg()).start()
        with chaos.stuck_backend(shots=1, max_stall_s=30.0):
            t1 = svc.submit(_mat(24, 24, seed=48))   # occupies the worker
            t2 = svc.submit(_mat(24, 24, seed=49))   # stays queued
            time.sleep(0.1)                          # t1 reaches dispatch
            svc.stop(drain=False, timeout=30.0)
        # Queued request finalized without a solve, and the IN-FLIGHT one
        # is cancelled cooperatively too (stop must not ride out the
        # 30 s stall) — both terminal.
        assert t2.result(timeout=5.0).status is SolveStatus.CANCELLED
        assert t1.result(timeout=5.0).status is SolveStatus.CANCELLED

    def test_inf_deadline_overrides_default(self):
        """deadline_s=inf means NO deadline even with a hostile default
        configured, and is exempt from the deadline budget — the warmup
        contract."""
        cfg = _cfg(default_deadline_s=0.0001, max_deadline_budget_s=1.0)
        with SVDService(cfg) as svc:
            res = svc.submit(_mat(16, 16, seed=96),
                             deadline_s=float("inf")).result(timeout=120.0)
            assert res.status is SolveStatus.OK
            # ...while the default still bites requests that don't opt out.
            r2 = svc.submit(_mat(16, 16, seed=97)).result(timeout=120.0)
        assert r2.status is SolveStatus.DEADLINE

    def test_warmup_compiles_degraded_variant(self):
        """`warmup(sigma_only=True)` pre-compiles the sigma-only variant
        per bucket, so a degraded dispatch never pays a compile
        mid-overload; warmup requests are ordinary manifest records."""
        with SVDService(_cfg()) as svc:
            svc.warmup(timeout=300.0)
            recs = svc.records()
            assert len(recs) == 2 * len(BUCKETS)
            assert all(r["status"] == "OK" for r in recs)
            ids = [r["request"]["id"] for r in recs]
            assert any(i.endswith("novec") for i in ids)
            # The degraded variant is now a cache hit: a sigma-only solve
            # completes fast and clean.
            res = svc.submit(_mat(20, 20, seed=95), compute_u=False,
                             compute_v=False).result(timeout=60.0)
            assert res.status is SolveStatus.OK and res.u is None


class TestDeadlinesAndCancellation:
    def test_deadline_mid_solve_neighbors_ok(self):
        """The acceptance scenario: a slowed request whose deadline
        expires mid-solve returns DEADLINE within one sweep of it, while
        the in-flight neighbors complete OK."""
        with SVDService(_cfg()) as svc:
            a = _mat(32, 32, seed=50)
            assert svc.submit(a).result(120.0).status is SolveStatus.OK
            # Wide margins: the deadline must comfortably outlive dispatch
            # + one slowed sweep (so sweeps >= 1) yet expire well before
            # convergence (~6 sweeps) — observed pre-sweep jitter under a
            # loaded suite is ~0.2 s.
            with chaos.slow_solve(0.7, shots=1):
                t_slow = svc.submit(a, deadline_s=1.0)
                t_n1 = svc.submit(_mat(28, 24, seed=51))
                t_n2 = svc.submit(a)
                r_slow = t_slow.result(timeout=60.0)
                r_n1 = t_n1.result(timeout=60.0)
                r_n2 = t_n2.result(timeout=60.0)
        assert r_slow.status is SolveStatus.DEADLINE
        # Partial: stopped at a sweep boundary before convergence.
        assert 1 <= r_slow.sweeps < 30
        assert r_n1.status is SolveStatus.OK
        assert r_n2.status is SolveStatus.OK

    def test_deadline_expired_in_queue(self):
        """A request whose deadline passes while QUEUED returns DEADLINE
        without spending a single sweep — and does NOT feed the breaker
        (queue-expired deadlines are overload symptoms; counting them
        would let overload trip the breaker onto the slower ladder path
        and amplify itself)."""
        with SVDService(_cfg()) as svc:
            with chaos.slow_solve(0.3, shots=1):
                t1 = svc.submit(_mat(32, 32, seed=52))       # slow occupier
                t2 = svc.submit(_mat(24, 24, seed=53), deadline_s=0.05)
                r2 = t2.result(timeout=60.0)
                assert t1.result(timeout=60.0).status is SolveStatus.OK
            assert svc.breaker.state() is BreakerState.CLOSED
        assert r2.status is SolveStatus.DEADLINE
        assert r2.sweeps == 0
        assert r2.solve_time_s is None          # never dispatched to a solve

    def test_cancel_while_queued(self):
        with SVDService(_cfg()) as svc:
            with chaos.slow_solve(0.3, shots=1):
                t1 = svc.submit(_mat(32, 32, seed=54))
                t2 = svc.submit(_mat(24, 24, seed=55))
                t2.cancel()
                r2 = t2.result(timeout=60.0)
                assert t1.result(timeout=60.0).status is SolveStatus.OK
        assert r2.status is SolveStatus.CANCELLED
        assert r2.solve_time_s is None          # never dispatched to a solve

    def test_cancel_mid_solve(self):
        with SVDService(_cfg()) as svc:
            with chaos.slow_solve(0.2, shots=1):
                t = svc.submit(_mat(32, 32, seed=56))
                time.sleep(0.3)                  # worker is mid-solve
                t.cancel()
                r = t.result(timeout=60.0)
        assert r.status is SolveStatus.CANCELLED


class TestBreakerAndBrownout:
    def test_stuck_backend_trips_breaker_ladder_recovers(self):
        """The acceptance scenario: chaos stuck_backend trips the breaker
        OPEN, the escalation ladder serves (and heals) the next request,
        a base-path probe closes it — and the WHOLE sequence is
        reconstructable from validated "serve" manifest records."""
        with SVDService(_cfg(breaker_threshold=2)) as svc:
            a = _mat(32, 32, seed=60)
            assert svc.submit(a).result(120.0).status is SolveStatus.OK
            with chaos.stuck_backend(shots=2, max_stall_s=10.0):
                # Deadlines comfortably longer than the dispatch latency
                # (the pre-dispatch expiry check must NOT fire — a stall
                # DURING the dispatch is a backend failure and must feed
                # the breaker) but far shorter than the stall.
                r1 = svc.submit(a, deadline_s=0.2).result(60.0)
                r2 = svc.submit(a, deadline_s=0.2).result(60.0)
            assert r1.status is SolveStatus.DEADLINE
            assert r2.status is SolveStatus.DEADLINE
            r3 = svc.submit(a).result(120.0)     # OPEN -> ladder
            r4 = svc.submit(a).result(120.0)     # HALF_OPEN -> base probe
            recs = svc.records()
        assert r3.status is SolveStatus.OK and r3.path == "ladder"
        assert r4.status is SolveStatus.OK and r4.path == "base"
        np.testing.assert_allclose(np.asarray(r3.s), _sref(a),
                                   rtol=1e-10, atol=1e-12)
        for r in recs:
            manifest.validate(r)
        seq = [(r["status"], r["path"], r["breaker"]) for r in recs]
        assert seq == [("OK", "base", "closed"),
                       ("DEADLINE", "base", "closed"),
                       ("DEADLINE", "base", "open"),
                       ("OK", "ladder", "half_open"),
                       ("OK", "base", "closed")]

    def test_brownout_sigma_only_then_shed(self):
        """Queue pressure walks the declared ladder in order: full SVD ->
        sigma-only (admitted, factors dropped, flagged degraded) -> shed
        (loud rejection) — decided at admission."""
        cfg = _cfg(max_queue_depth=10, brownout_sigma_only_at=0.3,
                   brownout_shed_at=0.6)
        with SVDService(cfg) as svc:
            with chaos.stuck_backend(shots=1, max_stall_s=3.0):
                first = svc.submit(_mat(16, 16, seed=61))  # stalls worker
                time.sleep(0.1)  # let it dispatch so depth is queue-only
                full, degraded = [], []
                # depth 0..2 -> FULL; depth 3..5 -> SIGMA_ONLY
                for i in range(6):
                    t = svc.submit(_mat(16, 16, seed=70 + i))
                    (degraded if svc.queue.depth() > 3 else full).append(t)
                with pytest.raises(AdmissionError) as ei:  # depth 6 -> SHED
                    svc.submit(_mat(16, 16, seed=80))
                assert ei.value.reason is AdmissionReason.BROWNOUT_SHED
                results = [t.result(timeout=120.0)
                           for t in [first] + full + degraded]
        assert all(r.status is SolveStatus.OK for r in results)
        assert not results[0].degraded
        # At least the LAST admitted request was admitted under
        # SIGMA_ONLY: factors dropped despite being requested.
        last = degraded[-1].result(0.0) if degraded else results[-1]
        assert last.degraded and last.u is None and last.v is None
        assert np.isfinite(np.asarray(last.s)).all()
        shed_recs = [r for r in svc.records()
                     if r["status"] == "REJECTED_BROWNOUT_SHED"]
        assert len(shed_recs) == 1 and shed_recs[0]["brownout"] == "SHED"
        # The ADMISSION-TIME level is what the records carry, so the
        # SIGMA_ONLY episode reconstructs from the manifest stream.
        assert sum(1 for r in svc.records()
                   if r["brownout"] == "SIGMA_ONLY") == len(degraded)


class TestServeManifest:
    def test_build_and_validate(self):
        rec = manifest.build_serve(
            request_id="r1", m=100, n=80, dtype="float32",
            bucket="128x96:float32", queue_wait_s=0.01, solve_time_s=0.5,
            status="OK", path="base", breaker="closed", brownout="FULL",
            degraded=False, sweeps=9)
        manifest.validate(rec)
        assert rec["kind"] == "serve"
        text = manifest.summarize(rec)
        assert "r1" in text and "128x96:float32" in text and "OK" in text

    def test_rejected_record_shape(self):
        rec = manifest.build_serve(
            request_id="r2", m=9999, n=9999, dtype="float32", bucket=None,
            queue_wait_s=0.0, solve_time_s=None,
            status="REJECTED_NO_BUCKET", path="rejected", breaker="closed",
            brownout="FULL", error="fits no declared bucket")
        manifest.validate(rec)
        assert "no bucket" in manifest.summarize(rec)

    def test_invalid_record_rejected(self):
        rec = manifest.build_serve(
            request_id="r3", m=8, n=8, dtype="float64", bucket="8x8:float64",
            queue_wait_s=0.0, solve_time_s=0.1, status="OK", path="base",
            breaker="closed", brownout="FULL")
        rec.pop("breaker")
        with pytest.raises(ValueError, match="breaker"):
            manifest.validate(rec)

    def test_service_appends_jsonl(self, tmp_path):
        path = tmp_path / "manifest.jsonl"
        with SVDService(_cfg(manifest_path=str(path))) as svc:
            svc.submit(_mat(16, 16, seed=90)).result(timeout=120.0)
        recs = manifest.load(path)
        assert len(recs) == 1
        manifest.validate(recs[0])
        assert recs[0]["kind"] == "serve" and recs[0]["status"] == "OK"


class TestServeRetraceContract:
    """The compile-cache contract: stepper entries compile once per
    BUCKET, never per request — and the guard demonstrably catches the
    violation when the budget is under-declared (a checker that cannot
    fail its fixture is decoration)."""

    ENTRIES = ("solver._sweep_step_jit", "solver._finish_jit")

    def _entries(self):
        from svd_jacobi_tpu import solver
        return {"solver._sweep_step_jit": solver._sweep_step_jit,
                "solver._finish_jit": solver._finish_jit}

    def _serve(self, buckets, shapes, seed0):
        cfg = ServeConfig(buckets=buckets, solver=SOLVER,
                          max_queue_depth=len(shapes) + 1)
        with SVDService(cfg) as svc:
            tickets = [svc.submit(_mat(m, n, seed=seed0 + i))
                       for i, (m, n) in enumerate(shapes)]
            for t in tickets:
                assert t.result(timeout=180.0).status is SolveStatus.OK

    def test_once_per_bucket_not_per_request(self):
        from svd_jacobi_tpu.analysis.recompile_guard import RecompileGuard
        buckets = ((40, 24, "float64"), (44, 44, "float64"))
        shapes = [(40, 24), (35, 20), (17, 38), (44, 44), (41, 30)]
        with RecompileGuard(budgets={e: 1 for e in self.ENTRIES},
                            entries=self._entries()) as guard:
            for e in self.ENTRIES:
                guard.expect(e, problems=len(buckets))
            self._serve(buckets, shapes, seed0=100)
            findings = guard.check()
        assert findings == [], [f.message for f in findings]

    def test_guard_catches_per_request_blowup(self):
        """Fixture: declare ONE problem but serve two buckets — the guard
        must flag the extra compilation (this is exactly what a request
        shape leaking past the bucket padding would look like)."""
        from svd_jacobi_tpu.analysis.recompile_guard import RecompileGuard
        buckets = ((28, 20, "float64"), (30, 30, "float64"))
        with RecompileGuard(budgets={e: 1 for e in self.ENTRIES},
                            entries=self._entries()) as guard:
            for e in self.ENTRIES:
                guard.expect(e, problems=1)   # under-declared on purpose
            self._serve(buckets, [(28, 20), (30, 30)], seed0=120)
            findings = guard.check()
        assert findings, "under-declared budget must produce RETRACE001"
        assert all(f.code == "RETRACE001" for f in findings)


@pytest.mark.soak
class TestSoak:
    def test_threaded_soak(self):
        """Satellite: N client threads, mixed bucket shapes, tight
        deadlines, one chaos-stuck request — no deadlock, every request
        terminal, the stuck request trips the breaker without poisoning
        its neighbors."""
        cfg = _cfg(max_queue_depth=64, breaker_threshold=1)
        svc = SVDService(cfg).start()
        a_warm = _mat(32, 32, seed=200)
        assert svc.submit(a_warm).result(180.0).status is SolveStatus.OK

        results = {}
        res_lock = threading.Lock()

        def put(key, res):
            with res_lock:
                results[key] = res

        # The designated victim goes FIRST (FIFO: first dispatch consumes
        # the armed stall) with a deadline far below the stall.
        with chaos.stuck_backend(shots=1, max_stall_s=10.0):
            victim = svc.submit(_mat(24, 24, seed=201), deadline_s=0.1)

            def client(cid):
                rng = np.random.default_rng(300 + cid)
                for j in range(4):
                    m = int(rng.integers(8, 49))
                    n = int(rng.integers(4, 33))
                    tight = (j == 2)   # one tight deadline per client
                    try:
                        t = svc.submit(
                            _mat(m, n, seed=1000 * cid + j),
                            deadline_s=(0.001 if tight else 120.0))
                    except AdmissionError as e:
                        put((cid, j), e.reason)
                        continue
                    try:
                        put((cid, j), t.result(timeout=240.0))
                    except TimeoutError:
                        put((cid, j), None)

            threads = [threading.Thread(target=client, args=(c,))
                       for c in range(5)]
            for th in threads:
                th.start()
            for th in threads:
                th.join(timeout=300.0)
            assert not any(th.is_alive() for th in threads), "client hung"
            r_victim = victim.result(timeout=60.0)
        # Drive recovery to completion: with threshold=1 a late tight
        # request may have re-tripped the breaker; at most two healthy
        # requests walk OPEN -> (ladder) HALF_OPEN -> (probe) CLOSED.
        for i in range(3):
            if svc.breaker.state() is BreakerState.CLOSED:
                break
            assert svc.submit(_mat(16, 16, seed=400 + i)).result(
                timeout=180.0).status is SolveStatus.OK
        svc.stop(drain=True, timeout=120.0)

        # Every request reached a terminal outcome (result, rejection —
        # never a hang).
        assert len(results) == 20
        assert all(v is not None for v in results.values()), results
        # The stuck request timed out loudly and tripped the breaker...
        assert r_victim.status is SolveStatus.DEADLINE
        recs = svc.records()
        for r in recs:
            manifest.validate(r)
        assert any(r["breaker"] == "open" for r in recs)
        # ...recovery ran through the ladder...
        assert any(r["path"] == "ladder" and r["status"] == "OK"
                   for r in recs)
        assert svc.breaker.state() is BreakerState.CLOSED
        # ...and it poisoned no neighbors: every non-tight client request
        # succeeded; tight ones are DEADLINE (or shed, loudly).
        for (cid, j), v in results.items():
            if isinstance(v, AdmissionReason):
                continue
            if j == 2:
                assert v.status in (SolveStatus.DEADLINE, SolveStatus.OK)
            else:
                assert v.status is SolveStatus.OK, (cid, j, v)


class TestServeDemoCli:
    def test_serve_demo_end_to_end(self, tmp_path, capsys, monkeypatch):
        """The `serve-demo` subcommand: seeded closed-loop clients, every
        request terminal, per-request records in the manifest."""
        import json
        # cli re-applies JAX_PLATFORMS from the environment, which would
        # flip the suite's forced-CPU backend onto a real attached TPU.
        monkeypatch.delenv("JAX_PLATFORMS", raising=False)
        from svd_jacobi_tpu import cli
        rc = cli.main(["serve-demo", "--requests", "6", "--clients", "2",
                       "--bucket", "32x24:float64", "--tight-frac", "0",
                       "--seed", "7", "--report-dir", str(tmp_path)])
        assert rc == 0
        out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        assert out["requests"] == 6 and out["terminal"] == 6
        assert out["errors"] == 0
        assert out["outcomes"].get("OK", 0) >= 1
        recs = manifest.load(tmp_path / "manifest.jsonl")
        assert len(recs) == 6
        for r in recs:
            manifest.validate(r)
            assert r["kind"] == "serve"


def test_brownout_enum_order():
    assert Brownout.FULL < Brownout.SIGMA_ONLY < Brownout.SHED


class TestTwoPhaseServing:
    """Two-phase σ-first serving: `submit(phase="sigma")` returns σ only
    and retains the solve's checkpointed stage; `Ticket.promote()`
    resumes THAT solve to full U/V (never a fresh solve); the
    content-addressed result cache finalizes byte-identical resubmits at
    admission with zero dispatch."""

    def test_sigma_then_promote_matches_oracle(self):
        a = _mat(30, 24, seed=501)
        with SVDService(_cfg()) as svc:
            t = svc.submit(a, phase="sigma")
            rs = t.result(timeout=120.0)
            assert rs.status is SolveStatus.OK
            assert rs.u is None and rs.v is None
            np.testing.assert_allclose(
                np.asarray(rs.s), np.linalg.svd(np.asarray(a),
                                                compute_uv=False),
                rtol=0, atol=1e-8)
            rp = t.promote(timeout=120.0)
            assert rp.status is SolveStatus.OK
            assert rp.request_id != t.request_id
            rec = (np.asarray(rp.u) * np.asarray(rp.s)) @ np.asarray(rp.v).T
            np.testing.assert_allclose(rec, np.asarray(a), atol=5e-12)
            # The σ-then-promote pair reconstructs from the stream.
            serve = [r for r in svc.records() if r.get("kind") == "serve"]
            assert serve[-2]["phase"] == "sigma"
            assert serve[-1]["phase"] == "promote"
            assert serve[-1]["promoted_from"] == t.request_id
            events = [(r["store"], r["event"]) for r in svc.records()
                      if r.get("kind") == "cache"]
            assert ("promotion", "retain") in events
            assert ("promotion", "promote") in events

    def test_promote_is_exactly_once_and_release_drops(self):
        from svd_jacobi_tpu.serve import PromotionError
        a = _mat(30, 24, seed=502)
        with SVDService(_cfg()) as svc:
            t = svc.submit(a, phase="sigma")
            t.result(timeout=120.0)
            assert t.promote(timeout=120.0).status is SolveStatus.OK
            with pytest.raises(PromotionError):
                t.promote(timeout=5.0)
            t2 = svc.submit(a + 1.0, phase="sigma")
            t2.result(timeout=120.0)
            assert t2.release() is True
            assert t2.release() is False
            with pytest.raises(PromotionError):
                t2.promote(timeout=5.0)
            # A full-phase ticket was never promotable.
            t3 = svc.submit(a, request_id="full-one")
            t3.result(timeout=120.0)
            with pytest.raises(PromotionError):
                t3.promote(timeout=5.0)

    def test_byte_budget_eviction_is_loud(self):
        from svd_jacobi_tpu.serve import PromotionError
        a = _mat(30, 24, seed=503)
        with SVDService(_cfg(promotion_store_bytes=1)) as svc:
            t = svc.submit(a, phase="sigma")
            assert t.result(timeout=120.0).status is SolveStatus.OK
            with pytest.raises(PromotionError, match="evicted|retained"):
                t.promote(timeout=5.0)
            events = [(r["store"], r["event"]) for r in svc.records()
                      if r.get("kind") == "cache"]
            assert ("promotion", "evict") in events
            assert ("promotion", "retain") not in events

    def test_wide_input_promote_restores_orientation(self):
        a = _mat(24, 30, seed=504)   # wide: the service transposes
        with SVDService(_cfg()) as svc:
            t = svc.submit(a, phase="sigma")
            t.result(timeout=120.0)
            rp = t.promote(timeout=120.0)
            assert np.asarray(rp.u).shape == (24, 24)
            assert np.asarray(rp.v).shape == (30, 24)
            rec = (np.asarray(rp.u) * np.asarray(rp.s)) @ np.asarray(rp.v).T
            np.testing.assert_allclose(rec, np.asarray(a), atol=5e-12)

    def test_explicit_sigma_refine_forces_full_finish(self, monkeypatch):
        """SVDConfig(sigma_refine=True) must NOT be silently dropped by
        the sigma-first termination: the compensated refinement needs
        the recombined factors, so factor-free and sigma-phase
        dispatches run the full finish stage (sigma requests retain the
        finished factors — promote still works, for free)."""
        from svd_jacobi_tpu import solver as _solver
        called = []
        orig = _solver.SweepStepper.sigma_finish
        monkeypatch.setattr(
            _solver.SweepStepper, "sigma_finish",
            lambda st, state: (called.append(1), orig(st, state))[1])
        cfg = _cfg(solver=SVDConfig(block_size=4, sigma_refine=True))
        a = _mat(30, 24, seed=546)
        with SVDService(cfg) as svc:
            r = svc.submit(a, compute_u=False,
                           compute_v=False).result(timeout=120.0)
            assert r.status is SolveStatus.OK
            t = svc.submit(a, phase="sigma")
            assert t.result(timeout=120.0).status is SolveStatus.OK
            rp = t.promote(timeout=120.0)
            assert rp.status is SolveStatus.OK
            rec = (np.asarray(rp.u) * np.asarray(rp.s)) @ np.asarray(rp.v).T
            np.testing.assert_allclose(rec, np.asarray(a), atol=5e-12)
        assert called == []    # refined σ comes from the full finish

    def test_degraded_brownout_reuses_sigma_phase_without_retention(self):
        """A SIGMA_ONLY-degraded full request serves σ through the SAME
        sigma-first termination but retains nothing — its solve
        accumulated no rotation product, so there is nothing to
        resume."""
        from svd_jacobi_tpu.serve import PromotionError
        cfg = _cfg(max_queue_depth=10, brownout_sigma_only_at=0.3,
                   brownout_shed_at=2.0)
        with SVDService(cfg) as svc:
            with chaos.stuck_backend(shots=1, max_stall_s=3.0):
                first = svc.submit(_mat(16, 16, seed=505))  # stalls worker
                time.sleep(0.1)
                fillers = [svc.submit(_mat(16, 16, seed=506 + i))
                           for i in range(4)]
                degraded = svc.submit(_mat(30, 24, seed=512))
                res = degraded.result(timeout=300.0)
                for t in [first] + fillers:
                    t.result(timeout=300.0)
            assert res.degraded and res.u is None and res.v is None
            assert np.isfinite(np.asarray(res.s)).all()
            retained = [r for r in svc.records()
                        if r.get("kind") == "cache"
                        and r.get("request_id") == degraded.request_id
                        and r["event"] == "retain"]
            assert retained == []

    def test_batched_all_sigma_promotes_per_member(self):
        cfg = _cfg(max_batch=4, batch_window_s=2.0, batch_tiers=(1, 4),
                   max_queue_depth=16)
        mats = [_mat(30, 24, seed=520 + i) for i in range(4)]
        with SVDService(cfg) as svc:
            tickets = [svc.submit(m, phase="sigma") for m in mats]
            results = [t.result(timeout=300.0) for t in tickets]
            assert all(r.status is SolveStatus.OK for r in results)
            tiers = {r.get("batch_tier") for r in svc.records()
                     if r.get("kind") == "serve"
                     and r.get("phase") == "sigma"}
            assert 4 in tiers    # genuinely coalesced
            for t, m in zip(tickets, mats):
                rp = t.promote(timeout=120.0)
                rec = ((np.asarray(rp.u) * np.asarray(rp.s))
                       @ np.asarray(rp.v).T)
                np.testing.assert_allclose(rec, np.asarray(m), atol=5e-12)

    def test_mixed_batch_sigma_member_promotes_from_result(self):
        cfg = _cfg(max_batch=2, batch_window_s=2.0, batch_tiers=(1, 2),
                   max_queue_depth=16)
        a_full, a_sig = _mat(30, 24, seed=530), _mat(30, 24, seed=531)
        with SVDService(cfg) as svc:
            tf = svc.submit(a_full)
            ts = svc.submit(a_sig, phase="sigma")
            rf, rs = tf.result(timeout=300.0), ts.result(timeout=300.0)
            assert rf.u is not None and rs.u is None
            rp = ts.promote(timeout=120.0)
            rec = (np.asarray(rp.u) * np.asarray(rp.s)) @ np.asarray(rp.v).T
            np.testing.assert_allclose(rec, np.asarray(a_sig), atol=5e-12)


class TestResultCache:
    def test_hit_finalizes_with_zero_dispatch(self):
        a = _mat(30, 24, seed=540)
        with SVDService(_cfg(result_cache_bytes=16 << 20)) as svc:
            r1 = svc.submit(a).result(timeout=120.0)
            before = svc.fleet.lanes[0].dispatches
            t2 = svc.submit(a)
            assert t2.done()          # finalized AT admission
            r2 = t2.result(timeout=1.0)
            assert svc.fleet.lanes[0].dispatches == before
            assert r2.path == "cache" and r2.status is SolveStatus.OK
            np.testing.assert_allclose(np.asarray(r2.s), np.asarray(r1.s))
            np.testing.assert_allclose(np.asarray(r2.u), np.asarray(r1.u))
            assert svc.stats().get("cache_hits") == 1
            serve = [r for r in svc.records() if r.get("kind") == "serve"]
            assert serve[-1]["path"] == "cache"

    def test_identity_covers_flags_and_orientation(self):
        a = _mat(30, 24, seed=541)
        with SVDService(_cfg(result_cache_bytes=16 << 20)) as svc:
            svc.submit(a).result(timeout=120.0)
            # Different factor flags: a miss (distinct identity).
            r = svc.submit(a, compute_u=False, compute_v=False).result(
                timeout=120.0)
            assert r.path != "cache"
            # The transposed twin must NOT share the entry.
            rt = svc.submit(np.asarray(a).T.copy()).result(timeout=120.0)
            assert rt.path != "cache"
            assert np.asarray(rt.u).shape[0] == 24

    def test_identity_covers_logical_shape(self):
        """Byte-identical buffers under DIFFERENT logical shapes can
        route to the same padded bucket — their factors differ, so the
        key must carry (m, n) or the second shape would be served the
        first one's decomposition."""
        buf = np.asarray(_mat(24, 24, seed=545)).reshape(-1)
        a1 = buf.reshape(24, 24)
        a2 = buf.reshape(32, 18)       # same bytes, same (32,32) bucket
        with SVDService(_cfg(result_cache_bytes=16 << 20)) as svc:
            r1 = svc.submit(a1).result(timeout=120.0)
            assert r1.status is SolveStatus.OK
            r2 = svc.submit(a2).result(timeout=120.0)
            assert r2.path != "cache"
            assert np.asarray(r2.u).shape == (32, 18)
            rec = (np.asarray(r2.u) * np.asarray(r2.s)) @ np.asarray(r2.v).T
            np.testing.assert_allclose(rec, a2, atol=5e-12)

    def test_invalidate_then_resolve(self):
        a = _mat(30, 24, seed=542)
        with SVDService(_cfg(result_cache_bytes=16 << 20)) as svc:
            svc.submit(a).result(timeout=120.0)
            assert svc.invalidate_cached() == 1
            r = svc.submit(a).result(timeout=120.0)
            assert r.path == "base"
            events = [(x["store"], x["event"]) for x in svc.records()
                      if x.get("kind") == "cache"]
            assert ("result", "invalidate") in events
            assert events.count(("result", "store")) == 2

    def test_degraded_and_partial_results_never_cached(self):
        a = _mat(30, 24, seed=543)
        with SVDService(_cfg(result_cache_bytes=16 << 20,
                             default_deadline_s=1e-9)) as svc:
            r = svc.submit(a).result(timeout=120.0)
            assert r.status is SolveStatus.DEADLINE
            stores = [x for x in svc.records() if x.get("kind") == "cache"
                      and x["event"] == "store"]
            assert stores == []

    def test_cache_disabled_by_default(self):
        a = _mat(30, 24, seed=544)
        with SVDService(_cfg()) as svc:
            svc.submit(a).result(timeout=120.0)
            r2 = svc.submit(a).result(timeout=120.0)
            assert r2.path == "base"
            assert not [x for x in svc.records()
                        if x.get("kind") == "cache"
                        and x["store"] == "result"]
