"""The bench driver surface (bench.py): flag guards and row modes.

bench.py is the driver-facing entry point (one JSON line per run, the
BASELINE.md table generator), so its flag semantics are part of the
framework's contract: --stepped must solve through the host-stepped API,
--fused-gen/--donate must refuse to fabricate "ours alone" baseline rows,
and incompatible combinations must fail loudly at parse time (mirroring
the CLI's parse-time rejections).
"""

import json
import os
import subprocess
import sys
from pathlib import Path

import pytest

# A solve-carrying bench row costs a full cold-cache subprocess run —
# slow lane. The parse-time flag rejections below stay tier-1 (they
# exit before any compile).
_row = pytest.mark.slow

BENCH = str(Path(__file__).resolve().parent.parent / "bench.py")


def _run(*args):
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    return subprocess.run(
        [sys.executable, BENCH, *args, "--platform=cpu"],
        capture_output=True, text=True, env=env, timeout=600)


@_row
def test_bench_stepped_row():
    p = _run("96", "--novec", "--no-baseline", "--reps=1", "--stepped")
    assert p.returncode == 0, p.stderr[-500:]
    row = json.loads(p.stdout.strip().splitlines()[-1])
    assert row["metric"] == "svd_96x96_float32_novec_gflops"
    assert row["sweeps"] >= 1 and row["value"] > 0


@_row
def test_bench_fused_gen_row():
    p = _run("96", "--novec", "--no-baseline", "--reps=1", "--fused-gen")
    assert p.returncode == 0, p.stderr[-500:]
    row = json.loads(p.stdout.strip().splitlines()[-1])
    assert row["value"] > 0


def test_bench_donate_requires_no_baseline():
    p = _run("96", "--donate", "--reps=1")
    assert p.returncode != 0
    assert "no-baseline" in (p.stderr + p.stdout)


def test_bench_fused_gen_stepped_conflict():
    p = _run("96", "--fused-gen", "--stepped", "--no-baseline")
    assert p.returncode != 0
    assert "incompatible" in (p.stderr + p.stdout)


def test_bench_check_row_gate_passes(tmp_path):
    """--check-row replays a saved row through the --check-against perf
    gate without solving (no backend, no compile — tier-1 fast): a value
    at/above the fitted band of the checked-in history exits 0."""
    row = tmp_path / "row.json"
    row.write_text(json.dumps(
        {"metric": "svd_2048x2048_float32_gflops", "value": 999.0,
         "unit": "GFLOP/s"}))
    p = _run(f"--check-row={row}", "--check-against=BENCH_r04.json")
    assert p.returncode == 0, p.stderr[-500:]
    assert "pass svd_2048x2048_float32_gflops" in p.stderr
    assert "best prior" in p.stderr


def test_bench_check_row_gate_fails_on_regression(tmp_path):
    row = tmp_path / "row.json"
    row.write_text(json.dumps(
        {"metric": "svd_2048x2048_float32_gflops", "value": 0.0001,
         "unit": "GFLOP/s"}))
    p = _run(f"--check-row={row}", "--check-against=BENCH_r04.json")
    assert p.returncode == 4, (p.returncode, p.stderr[-500:])
    assert "FAIL" in p.stderr


def test_bench_check_row_requires_check_against(tmp_path):
    row = tmp_path / "row.json"
    row.write_text("{}")
    p = _run(f"--check-row={row}")
    assert p.returncode != 0
    assert "check-against" in (p.stderr + p.stdout)


@_row
def test_bench_donate_stepped_row():
    """The 30208^2 recipe's flag combination, exercised end-to-end at toy
    size: stepped solve, input released after init, sigma still correct
    enough to produce a row."""
    p = _run("96", "--novec", "--no-baseline", "--reps=1", "--stepped",
             "--donate", "--precondition=off")
    assert p.returncode == 0, p.stderr[-500:]
    row = json.loads(p.stdout.strip().splitlines()[-1])
    assert row["value"] > 0
