"""Restart-survivable serving: entry registry + AOT persistent cache +
durable request journal (tests/test_restart.py).

The acceptance pair this file proves:

  * a RESTARTED process with a warm persistent executable cache serves
    with ZERO fresh compilations (``backend_compiles - cache_hits == 0``
    under `RecompileGuard` — in current JAX the backend-compile event
    fires on cache hits too, so the subtraction is the honest count);
  * a SIGKILL'd service under load resumes every journaled unfinalized
    request EXACTLY ONCE after restart — no lost requests, no duplicate
    finalizations.

Subprocess lanes (`-m chaos`) drive tests/_restart_worker.py: real
SIGKILL, real process boundaries (an in-process "restart" would be faked
by the live jit caches). In-process lanes cover the registry/budget
bijection (AOT001 + its seeded fixtures), journal mechanics (write-ahead
order, torn-line quarantine, checksums, atomic rewrite), recovery
semantics (queue-front re-admission, wall-clock deadline decay, loud
terminalization of expired/corrupt debt), zero-downtime reload, and the
"coldstart" manifest record.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time
import warnings
from pathlib import Path

import numpy as np
import pytest

import jax.numpy as jnp

from svd_jacobi_tpu import SVDConfig
from svd_jacobi_tpu import config as sj_config
from svd_jacobi_tpu.analysis import aot_checks
from svd_jacobi_tpu.obs import manifest
from svd_jacobi_tpu.serve import (EntryRegistry, Journal, Request,
                                  ServeConfig, SVDService, Ticket)
from svd_jacobi_tpu.serve import journal as journal_mod
from svd_jacobi_tpu.serve import registry as serve_registry
from svd_jacobi_tpu.utils import matgen

_WORKER = Path(__file__).parent / "_restart_worker.py"

_BUCKETS = ((48, 32, "float32"), (64, 48, "float32"))


def _cfg(**over):
    base = dict(buckets=_BUCKETS,
                solver=SVDConfig(pair_solver="pallas"),
                max_queue_depth=32,
                brownout_sigma_only_at=2.0, brownout_shed_at=2.0)
    base.update(over)
    return ServeConfig(**base)


def _run_worker(*argv, timeout=400.0):
    return subprocess.run(
        [sys.executable, str(_WORKER), *argv],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        timeout=timeout, env={**os.environ, "JAX_PLATFORMS": "cpu"})


# ---------------------------------------------------------------------------
# AOT001: registry <-> RETRACE_BUDGETS bijection (+ seeded fixtures).


@pytest.mark.serve
class TestAOT001:
    def test_registry_budget_bijection_clean(self):
        assert aot_checks.check_budget_coverage() == []

    def test_plan_names_clean(self):
        assert aot_checks.check_plan_names() == []

    def test_seeded_missing_registry_entry_fires(self):
        """A budget whose entry the registry does not enumerate is dead
        declaration — AOT001 must fire (the seeded fixture)."""
        budgets = {**sj_config.RETRACE_BUDGETS, "solver._phantom_jit": 1}
        findings = aot_checks.check_budget_coverage(budgets=budgets)
        assert [f.code for f in findings] == ["AOT001"]
        assert "solver._phantom_jit" in findings[0].where

    def test_seeded_unbudgeted_registry_entry_fires(self):
        entries = dict(serve_registry.jit_entries())
        dropped = "solver._tsqr_jit"
        budgets = {k: v for k, v in sj_config.RETRACE_BUDGETS.items()
                   if k != dropped}
        findings = aot_checks.check_budget_coverage(budgets=budgets,
                                                    entries=entries)
        assert [f.code for f in findings] == ["AOT001"]
        assert dropped in findings[0].where

    def test_seeded_unbudgeted_plan_name_fires(self):
        budgets = {k: v for k, v in sj_config.RETRACE_BUDGETS.items()
                   if k != "solver._sketch_project_jit"}
        findings = aot_checks.check_plan_names(budgets=budgets)
        assert findings and all(f.code == "AOT001" for f in findings)

    def test_analysis_main_wires_aot_pass(self, capsys):
        """The `aot` pass is selectable through `python -m
        svd_jacobi_tpu.analysis` (in-process: the pass is pure
        set-comparison + eval_shape, no fresh backend needed)."""
        from svd_jacobi_tpu.analysis.__main__ import main as analysis_main
        rc = analysis_main(["--passes", "aot", "--report-dir", "off"])
        assert rc == 0
        out = capsys.readouterr().out.strip().splitlines()[-1]
        assert json.loads(out)["passes"]["aot"] is True


# ---------------------------------------------------------------------------
# The entry registry.


@pytest.mark.serve
class TestEntryRegistry:
    def test_enumeration_deterministic_and_complete(self):
        svc = SVDService(_cfg(max_batch=4, batch_tiers=(1, 4)))
        keys = svc.registry.entries()
        assert keys == svc.registry.entries()      # deterministic
        names = [k.name for k in keys]
        assert len(set(names)) == len(names)       # unique coordinates
        # Per bucket: vec + novec singles, plus the tier-4 batched pair.
        assert sum(1 for k in keys if k.tier is None) == 4
        assert sum(1 for k in keys if k.tier == 4) == 4
        # sigma_only=False drops the novec variants.
        assert all(k.compute_u for k in
                   svc.registry.entries(sigma_only=False))

    def test_reachable_tiers_respect_max_batch(self):
        svc = SVDService(_cfg(max_batch=3, batch_tiers=(1, 2, 8)))
        b = svc.buckets.buckets[0]
        # Batches of 2..3 snap to tiers {2, 8-capped-by-3 -> 8? no:
        # reachable = {min tier >= c for c in 2..3} = {2, 8}.
        assert svc.registry.reachable_tiers(b) == (2, 8)

    def test_aot_plan_names_are_budgeted(self):
        svc = SVDService(_cfg(max_batch=4, batch_tiers=(1, 4)))
        for key in svc.registry.entries():
            for name, fn, args, kwargs in svc.registry.aot_plan(key):
                assert name in sj_config.RETRACE_BUDGETS, (key.name, name)

    def test_rank_families_plan_stage_jits(self):
        svc = SVDService(_cfg(buckets=((256, 32, "float32", "tall"),
                                       (96, 96, "float32", "topk", 8))))
        plans = {k.bucket.kind: [p[0] for p in svc.registry.aot_plan(k)]
                 for k in svc.registry.entries(sigma_only=False)}
        assert "solver._tsqr_jit" in plans["tall"]
        assert "solver._lift_q_jit" in plans["tall"]
        assert "solver._sketch_project_jit" in plans["topk"]
        assert "solver._lift_q_jit" in plans["topk"]

    def test_aot_compile_then_live_serve_matches(self):
        """An AOT-compiled entry's programs must be the ones the live
        dispatch requests: after aot_compile, serving a request through
        the same bucket keeps every stepper entry within its retrace
        budget (the plan cannot drift from the executed path)."""
        from svd_jacobi_tpu.analysis.recompile_guard import RecompileGuard
        svc = SVDService(_cfg(buckets=((40, 24, "float32"),)))
        key = svc.registry.entries(sigma_only=False)[0]
        svc.registry.aot_compile(key)
        with RecompileGuard() as guard:
            guard.expect("solver._sweep_step_pallas_jit", problems=1)
            with svc:
                res = svc.submit(matgen.random_dense(
                    40, 24, seed=3, dtype=jnp.float32)).result(300.0)
        assert res.status is not None and res.status.name == "OK"
        assert guard.check() == []


# ---------------------------------------------------------------------------
# Journal mechanics.


def _mk_request(svc, rid="jr-0", m=40, n=30, deadline_s=None, seed=0):
    bucket = svc.buckets.route(m, n, "float32")
    ticket = Ticket(rid)
    rng = np.random.default_rng(seed)
    a = rng.standard_normal((m, n)).astype(np.float32)
    now = time.monotonic()
    return Request(id=rid, a=a, m=m, n=n, orig_shape=(m, n),
                   transposed=False, bucket=bucket, compute_u=True,
                   compute_v=True, degraded=False,
                   deadline=(None if deadline_s is None
                             else now + deadline_s),
                   deadline_s=deadline_s, submitted=now,
                   cancel=ticket._cancel, ticket=ticket)


@pytest.mark.serve
class TestJournal:
    def test_lifecycle_roundtrip(self, tmp_path):
        svc = SVDService(_cfg())
        j = Journal(tmp_path / "j.jsonl")
        req = _mk_request(svc, "jr-1", deadline_s=5.0)
        j.append_admit(req)
        j.append_dispatch("jr-1", lane=0)
        state = j.scan()
        assert list(state.admits) == ["jr-1"]
        assert "jr-1" in state.dispatched
        assert [r["id"] for r in state.unfinalized] == ["jr-1"]
        j.append_finalize("jr-1", "OK")
        state = j.scan()
        assert state.finalized == {"jr-1": "OK"}
        assert state.unfinalized == []
        # The journaled payload reconstructs bit-exactly.
        a = journal_mod.decode_array(state.admits["jr-1"]["input"])
        np.testing.assert_array_equal(a, np.asarray(req.a))

    def test_payload_checksum_mismatch_raises(self, tmp_path):
        svc = SVDService(_cfg())
        j = Journal(tmp_path / "j.jsonl")
        j.append_admit(_mk_request(svc, "jr-2"))
        rec = j.scan().admits["jr-2"]
        rec["input"]["data_sha256"] = "0" * 64
        with pytest.raises(ValueError, match="checksum"):
            journal_mod.decode_array(rec["input"])

    def test_torn_trailing_line_quarantined(self, tmp_path):
        svc = SVDService(_cfg())
        path = tmp_path / "j.jsonl"
        j = Journal(path)
        j.append_admit(_mk_request(svc, "jr-3"))
        with path.open("a") as f:
            f.write('{"kind": "admit", "id": "torn", "trunc')  # no \n
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            state = j.scan()
        assert state.torn == 1
        assert list(state.admits) == ["jr-3"]   # history survives
        assert (tmp_path / "j.jsonl.torn").exists()
        assert any("quarantined" in str(x.message) for x in w)
        # The crash-safe appender inserts a newline first, so the next
        # record can never concatenate into the torn fragment.
        j.append_finalize("jr-3", "OK")
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            assert j.scan().finalized == {"jr-3": "OK"}

    def test_rewrite_is_atomic_compaction(self, tmp_path):
        svc = SVDService(_cfg())
        j = Journal(tmp_path / "j.jsonl")
        for i in range(3):
            j.append_admit(_mk_request(svc, f"jr-{i}", seed=i))
        keep = [j.scan().admits["jr-1"]]
        j.rewrite(keep)
        state = j.scan()
        assert list(state.admits) == ["jr-1"]
        assert not (tmp_path / "j.jsonl.tmp").exists()

    def test_manifest_load_tolerates_torn_tail(self, tmp_path):
        path = tmp_path / "manifest.jsonl"
        rec = manifest.build_fleet(event="probe", lane=0, ok=True)
        manifest.append(path, rec)
        with path.open("a") as f:
            f.write('{"kind": "serve", "trunc')
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            records = manifest.load(path)
        assert len(records) == 1 and records[0]["kind"] == "fleet"
        assert any("quarantined" in str(x.message) for x in w)
        # Appending after the torn tail self-repairs the stream.
        manifest.append(path, rec)
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            assert len(manifest.load(path)) == 2

    def test_coldstart_record_roundtrip(self, tmp_path):
        rec = manifest.build_coldstart(
            entries=[{"entry": "l0/48x32:float32/vec", "time_s": 1.25,
                      "cache_hit": True, "backend_compiles": 4,
                      "cache_hits": 4, "fresh_compiles": 0,
                      "jits": ["solver._sweep_step_pallas_jit"]}],
            total_s=2.5, backend_compiles=8, cache_hits=8,
            fresh_compiles=0, cache_dir="/tmp/x",
            config_sha256="ab" * 32)
        manifest.validate(rec)
        path = manifest.append(tmp_path / "m.jsonl", rec)
        loaded = manifest.load(path)[0]
        assert loaded["kind"] == "coldstart"
        assert loaded["fresh_compiles"] == 0
        assert "coldstart" in manifest.summarize(loaded)
        with pytest.raises(ValueError):
            manifest.validate({**rec, "entries": [{"entry": 1}]})


# ---------------------------------------------------------------------------
# Recovery semantics (in-process: journal written by hand, replayed by a
# fresh service — the subprocess SIGKILL lane covers the real kill).


@pytest.mark.serve
class TestRecover:
    def test_recover_readmits_serves_and_compacts(self, tmp_path):
        jpath = tmp_path / "j.jsonl"
        writer = SVDService(_cfg(journal_path=str(jpath)))
        j = writer.journal
        for i in range(3):
            j.append_admit(_mk_request(writer, f"rq-{i}", seed=i,
                                       deadline_s=600.0))
        j.append_finalize("rq-0", "OK")       # already served pre-crash
        # The writer plays a CRASHED process: a real crash leaves a
        # dead-pid lockfile the successor auto-breaks; in-process the
        # pid stays live, so stand in for the death by releasing.
        writer.journal.release()
        svc = SVDService(_cfg(journal_path=str(jpath)))
        tickets = svc.recover()
        assert sorted(tickets) == ["rq-1", "rq-2"]
        # Queue front, admit order preserved.
        assert [r.id for r in svc.queue._q] == ["rq-1", "rq-2"]
        # Journal compacted to exactly the debt, attempts bumped.
        state = Journal(jpath).scan()
        assert sorted(state.admits) == ["rq-1", "rq-2"]
        assert all(r["attempt"] == 2 for r in state.admits.values())
        with svc:
            for t in tickets.values():
                res = t.result(timeout=300.0)
                assert res.status is not None and res.status.name == "OK"
        final = Journal(jpath).scan()
        assert final.finalized == {"rq-1": "OK", "rq-2": "OK"}
        assert final.unfinalized == []
        rec = [r for r in svc.records()
               if r.get("event") == "journal_recover"]
        assert rec and rec[0]["count"] == 2

    def test_expired_deadline_terminalizes_loudly(self, tmp_path):
        jpath = tmp_path / "j.jsonl"
        writer = SVDService(_cfg(journal_path=str(jpath)))
        req = _mk_request(writer, "rq-exp", deadline_s=5.0)
        # The original admit was 60 wall-seconds ago: the 5 s budget is
        # long spent — recovery must honor it, not resurrect it.
        writer.journal.append_admit(req, admitted_wall=time.time() - 60.0)
        writer.journal.release()   # stand in for the dead owner
        svc = SVDService(_cfg(journal_path=str(jpath)))
        tickets = svc.recover()
        res = tickets["rq-exp"].result(timeout=5.0)
        assert res.status is not None and res.status.name == "DEADLINE"
        recs = [r for r in svc.records()
                if r.get("kind") == "serve" and r.get("path") == "recovery"]
        assert recs and recs[0]["status"] == "DEADLINE"
        assert Journal(jpath).scan().unfinalized == []

    def test_corrupt_payload_terminalizes_error(self, tmp_path):
        jpath = tmp_path / "j.jsonl"
        writer = SVDService(_cfg(journal_path=str(jpath)))
        writer.journal.append_admit(_mk_request(writer, "rq-bad",
                                                deadline_s=600.0))
        records, _ = manifest.read_jsonl_tolerant(jpath)
        records[0]["input"]["data_sha256"] = "0" * 64
        jpath.write_text("\n".join(json.dumps(r) for r in records) + "\n")
        writer.journal.release()   # stand in for the dead owner
        svc = SVDService(_cfg(journal_path=str(jpath)))
        tickets = svc.recover()
        res = tickets["rq-bad"].result(timeout=5.0)
        assert res.error is not None and "checksum" in res.error

    def test_recover_advances_auto_request_ids(self, tmp_path):
        """A restarted process's auto-id counter restarts at r00000; a
        new submit must never reuse a journaled id (the journal and the
        manifest key exactly-once accounting by id)."""
        jpath = tmp_path / "j.jsonl"
        writer = SVDService(_cfg(journal_path=str(jpath)))
        for i in range(3):
            writer.journal.append_admit(
                _mk_request(writer, f"r{i:05d}", seed=i, deadline_s=600.0))
        writer.journal.append_finalize("r00001", "OK")
        writer.journal.release()   # stand in for the dead owner
        svc = SVDService(_cfg(journal_path=str(jpath)))
        tickets = svc.recover()
        assert sorted(tickets) == ["r00000", "r00002"]
        with svc:
            for t in tickets.values():
                t.result(timeout=300.0)
            svc.submit(matgen.random_dense(40, 30, seed=9,
                                           dtype=jnp.float32)
                       ).result(timeout=300.0)
        state = Journal(jpath).scan()
        fresh = sorted(set(state.admits) - {"r00000", "r00002"})
        # Past EVERY journaled id — the finalized r00001 included.
        assert fresh == ["r00003"]

    def test_write_ahead_submit_and_finalize(self, tmp_path):
        """The live submit path journals before enqueue and finalizes
        after the ticket wins — the whole lifecycle lands on disk."""
        jpath = tmp_path / "j.jsonl"
        with SVDService(_cfg(journal_path=str(jpath))) as svc:
            res = svc.submit(matgen.random_dense(40, 30, seed=5,
                                                 dtype=jnp.float32),
                             request_id="live-0").result(timeout=300.0)
        assert res.status is not None and res.status.name == "OK"
        state = Journal(jpath).scan()
        assert list(state.admits) == ["live-0"]
        assert "live-0" in state.dispatched
        assert state.finalized == {"live-0": "OK"}


# ---------------------------------------------------------------------------
# Zero-downtime reload.


@pytest.mark.serve
class TestReload:
    def test_reload_swaps_bucket_set_atomically(self):
        with SVDService(_cfg(buckets=((48, 32, "float32"),))) as svc:
            ok = svc.submit(matgen.random_dense(40, 30, seed=1,
                                                dtype=jnp.float32))
            assert ok.result(300.0).status.name == "OK"
            # 100x80 fits no declared bucket yet.
            with pytest.raises(Exception):
                svc.submit(matgen.random_dense(100, 80, seed=2,
                                               dtype=jnp.float32))
            done = svc.reload(buckets=((48, 32, "float32"),
                                       (112, 80, "float32")),
                              warm=False)
            assert done.wait(60.0)
            assert svc._last_reload_error is None
            res = svc.submit(matgen.random_dense(100, 80, seed=3,
                                                 dtype=jnp.float32)
                             ).result(timeout=300.0)
            assert res.status is not None and res.status.name == "OK"
            # The old bucket still serves (drain grace).
            res2 = svc.submit(matgen.random_dense(40, 30, seed=4,
                                                  dtype=jnp.float32)
                              ).result(timeout=300.0)
            assert res2.status.name == "OK"
            assert svc.stats().get("reloads") == 1
            assert any(r.get("event") == "reload" for r in svc.records())

    def test_failed_reload_swaps_nothing(self):
        with SVDService(_cfg(buckets=((48, 32, "float32"),))) as svc:
            before = svc.buckets.buckets
            done = svc.reload(buckets=("not-a-bucket-spec",), warm=False,
                              background=False)
            assert done.is_set()
            assert svc._last_reload_error is not None
            assert svc.buckets.buckets == before
            res = svc.submit(matgen.random_dense(40, 30, seed=6,
                                                 dtype=jnp.float32)
                             ).result(timeout=300.0)
            assert res.status.name == "OK"


# ---------------------------------------------------------------------------
# The subprocess acceptance lanes: real SIGKILL, real restart, real
# persistent cache across a process boundary.


@pytest.mark.chaos
@pytest.mark.slow
class TestSigkillResume:
    """Real-subprocess SIGKILL drill — full cold-cache recompiles per
    process, so it rides the slow lane (`-m 'chaos and slow'`); the
    in-process recover/journal contracts above stay tier-1."""

    def test_sigkill_under_load_resumes_exactly_once(self, tmp_path):
        jpath = tmp_path / "journal.jsonl"
        serve = _run_worker("serve", "--journal", str(jpath),
                            "--requests", "3", "--kill-after", "2")
        assert serve.returncode == -9, (serve.returncode,
                                        serve.stderr[-2000:])
        state = Journal(jpath).scan()
        debt = [r["id"] for r in state.unfinalized]
        finalized_before = dict(state.finalized)
        assert debt, "the kill must strand unfinalized requests"
        assert finalized_before, "the kill must come after some service"
        resume = _run_worker("resume", "--journal", str(jpath))
        assert resume.returncode == 0, resume.stderr[-2000:]
        out = json.loads(resume.stdout.strip().splitlines()[-1])
        # Every journaled unfinalized request resumed, none lost.
        assert sorted(out["resumed"]) == sorted(debt)
        assert all(s == "OK" for s in out["results"].values())
        # Exactly-once: nothing finalized twice across the boundary, and
        # nothing is still owed.
        assert not set(out["results"]) & set(finalized_before)
        assert out["journal_unfinalized"] == []
        assert sorted(out["journal_finalized"]) == sorted(debt)


@pytest.mark.chaos
class TestPersistentCacheRestart:
    @pytest.mark.slow
    def test_restart_cold_warm_corrupt_lifecycle(self, tmp_path):
        """THE cold-start acceptance, one cache directory, three
        restarts (each a real subprocess — an in-process 'restart' would
        be faked by the live jit caches): (1) cold — fresh compiles;
        (2) warm — the restarted process warms up and serves with ZERO
        fresh compilations (every backend compile served by the
        persistent cache); (3) a corrupted cache entry degrades to a
        LOUD warning + fresh recompile, never a crash or a garbage
        executable."""
        cache = str(tmp_path / "cache")
        cold = _run_worker("cachecheck", "--cache", cache)
        assert cold.returncode == 0, cold.stderr[-2000:]
        cold_out = json.loads(cold.stdout.strip().splitlines()[-1])
        assert cold_out["status"] == "OK"
        assert cold_out["fresh_backend_compiles"] > 0
        warm = _run_worker("cachecheck", "--cache", cache)
        assert warm.returncode == 0, warm.stderr[-2000:]
        warm_out = json.loads(warm.stdout.strip().splitlines()[-1])
        assert warm_out["status"] == "OK"
        assert warm_out["fresh_backend_compiles"] == 0, warm_out
        assert warm_out["cache_hits"] > 0
        hurt = _run_worker("cachecheck", "--cache", cache, "--corrupt")
        assert hurt.returncode == 0, hurt.stderr[-2000:]
        out = json.loads(hurt.stdout.strip().splitlines()[-1])
        assert out["status"] == "OK"
        assert any("compilation cache" in w for w in out["warnings"]), \
            out["warnings"]
        assert out["fresh_backend_compiles"] > 0

    def test_stale_cache_manifest_quarantined(self, tmp_path):
        """A namespace whose CACHE_MANIFEST disagrees with its expected
        identity is quarantined with a loud warning — never served."""
        ns = tmp_path / "ns"
        ns.mkdir()
        (ns / serve_registry.CACHE_MANIFEST_NAME).write_text(
            json.dumps({"config_sha256": "different"}))
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            ok = serve_registry.verify_cache(
                ns, {"config_sha256": "expected"})
        assert ok is False
        assert not ns.exists()          # renamed aside
        assert any("quarantined" in str(x.message) for x in w)
        quarantined = list(tmp_path.glob("ns.quarantined-*"))
        assert len(quarantined) == 1
        # An unreadable manifest takes the same lane.
        ns2 = tmp_path / "ns2"
        ns2.mkdir()
        (ns2 / serve_registry.CACHE_MANIFEST_NAME).write_text("{trunc")
        with warnings.catch_warnings(record=True):
            warnings.simplefilter("always")
            assert serve_registry.verify_cache(
                ns2, {"config_sha256": "expected"}) is False


@pytest.mark.chaos
@pytest.mark.slow
class TestRestartDrill:
    def test_cli_restart_drill_loses_nothing(self):
        out = subprocess.run(
            [sys.executable, "-m", "svd_jacobi_tpu.cli", "serve-demo",
             "--restart-drill", "--drill-requests", "4",
             "--report-dir", "off"],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            timeout=600.0, env={**os.environ, "JAX_PLATFORMS": "cpu"})
        assert out.returncode == 0, (out.stdout[-2000:],
                                     out.stderr[-2000:])
        summary = json.loads(out.stdout.strip().splitlines()[-1])
        assert summary["lost"] == []
        assert summary["resumed"] >= len(summary["unfinalized_at_kill"])
        assert summary["cold_start_s"] is not None


class TestJournalPayloadModes:
    """ServeConfig.journal_payload: "digest" journals the SHA-256 +
    shape/dtype instead of the base64 bytes (PROFILE.md item 26's
    dominant per-request tax) — and a digest-only request whose bytes
    are gone finalizes ERROR path="recovery" LOUDLY on replay, never
    silently."""

    def test_digest_mode_shrinks_the_journal(self, tmp_path):
        full_p, dig_p = tmp_path / "full.jsonl", tmp_path / "dig.jsonl"
        writer = SVDService(_cfg(journal_path=str(full_p)))
        req = _mk_request(writer, "jp-0", seed=3)
        writer.journal.append_admit(req, payload_mode="full")
        Journal(dig_p).append_admit(req, payload_mode="digest")
        full_size, dig_size = (full_p.stat().st_size,
                               dig_p.stat().st_size)
        # 40x30 f32 = 4.7 KiB raw -> ~6.3 KiB of base64; the digest
        # record drops the payload to O(100 B) of metadata.
        assert dig_size < full_size / 5
        rec = Journal(dig_p).scan().admits["jp-0"]
        assert "data_b64" not in rec["input"]
        assert len(rec["input"]["data_sha256"]) == 64
        assert rec["input"]["shape"] == [40, 30]

    def test_digest_mode_recovery_is_loudly_error(self, tmp_path):
        jpath = tmp_path / "j.jsonl"
        writer = SVDService(_cfg(journal_path=str(jpath),
                                 journal_payload="digest"))
        writer.journal.append_admit(_mk_request(writer, "jp-1",
                                                deadline_s=600.0),
                                    payload_mode="digest")
        writer.journal.release()   # stand in for the dead owner
        svc = SVDService(_cfg(journal_path=str(jpath)))
        tickets = svc.recover()
        res = tickets["jp-1"].result(timeout=5.0)
        assert res.error is not None
        assert "digest-only" in res.error
        recs = [r for r in svc.records()
                if r.get("kind") == "serve" and r.get("path") == "recovery"]
        assert recs and recs[0]["status"] == "ERROR"
        # The debt is settled (finalized), not replayed forever.
        assert Journal(jpath).scan().unfinalized == []

    def test_submit_journals_in_configured_mode(self, tmp_path):
        jpath = tmp_path / "j.jsonl"
        with SVDService(_cfg(journal_path=str(jpath),
                             journal_payload="digest")) as svc:
            rng = np.random.default_rng(7)
            a = rng.standard_normal((40, 30)).astype(np.float32)
            svc.submit(a, request_id="jp-2").result(timeout=300.0)
        rec = Journal(jpath).scan().admits["jp-2"]
        assert "data_b64" not in rec["input"]

    def test_invalid_mode_rejected(self, tmp_path):
        with pytest.raises(ValueError, match="journal_payload"):
            SVDService(_cfg(journal_path=str(tmp_path / "j.jsonl"),
                            journal_payload="compressed"))
