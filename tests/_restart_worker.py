"""Subprocess driver for the restart-survivability lane
(tests/test_restart.py): a serving process that can be SIGKILL'd
mid-load and a resume process that recovers the journal — run as

    python tests/_restart_worker.py serve  --journal J [--cache C] ...
    python tests/_restart_worker.py resume --journal J [--cache C]
    python tests/_restart_worker.py cachecheck --cache C [--corrupt]

``serve`` arms `chaos.sigkill_at_dispatch(--kill-after)`: the process
journals its admitted requests, serves until the armed dispatch, then
takes a REAL SIGKILL (no cleanup, no final snapshot) with requests
queued and in flight. ``resume`` builds a fresh service on the same
journal, replays it, serves every recovered request, and prints one JSON
line of results. ``cachecheck`` proves the persistent-cache fallback:
warm the cache, (optionally) corrupt an entry, and report whether the
solve still succeeds, what warning fired, and the fresh-compile count.
"""

import argparse
import json
import os
import sys
import warnings

os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=2")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)


BUCKET = (48, 32, "float32")


def _service(args, **overrides):
    from svd_jacobi_tpu import SVDConfig
    from svd_jacobi_tpu.serve import ServeConfig, SVDService
    kw = dict(
        buckets=(BUCKET,),
        solver=SVDConfig(pair_solver="pallas"),
        journal_path=args.journal,
        compile_cache_dir=args.cache,
        max_queue_depth=64,
        brownout_sigma_only_at=2.0, brownout_shed_at=2.0)
    kw.update(overrides)
    return SVDService(ServeConfig(**kw))


def cmd_serve(args) -> int:
    import numpy as np

    from svd_jacobi_tpu.resilience import chaos
    svc = _service(args)
    svc.start()
    if args.warmup:
        svc.warmup(timeout=300.0)
    # Slow every dispatch a little so the parent-observable window
    # between "journaled" and "finalized" is wide; deterministic.
    slow = chaos.slow_solve(args.slow_s, shots=args.requests)
    slow.__enter__()
    chaos.sigkill_at_dispatch(args.kill_after)
    rng = np.random.default_rng(args.seed)
    tickets = []
    for i in range(args.requests):
        a = rng.standard_normal((40, 30)).astype(np.float32)
        tickets.append(svc.submit(a, deadline_s=args.deadline_s,
                                  request_id=f"req-{i:02d}"))
    print(json.dumps({"submitted": [t.request_id for t in tickets]}),
          flush=True)
    # Block until the armed SIGKILL lands (it will: the worker dispatches
    # request after request). If it somehow does not, exit 3 loudly.
    for t in tickets:
        t.result(timeout=300.0)
    return 3


def cmd_resume(args) -> int:
    import time
    t_proc = time.perf_counter()
    from svd_jacobi_tpu.analysis.recompile_guard import RecompileGuard
    with RecompileGuard() as guard:
        svc = _service(args)
        tickets = svc.recover()
        svc.start()
        first_done_s = None
        results = {}
        for rid, t in tickets.items():
            res = t.result(timeout=300.0)
            if first_done_s is None:
                first_done_s = time.perf_counter() - t_proc
            results[rid] = (res.status.name if res.status is not None
                            else f"ERROR:{res.error}")
        svc.stop(drain=True, timeout=60.0)
    from svd_jacobi_tpu.serve import Journal
    state = Journal(args.journal).scan()
    print(json.dumps({
        "resumed": sorted(tickets),
        "results": results,
        "first_result_s": first_done_s,
        "journal_finalized": state.finalized,
        "journal_unfinalized": [r["id"] for r in state.unfinalized],
        "backend_compiles": guard.backend_compiles,
        "cache_hits": guard.cache_hits,
        "fresh_backend_compiles": guard.fresh_backend_compiles(),
    }), flush=True)
    return 0


def cmd_cachecheck(args) -> int:
    import numpy as np

    from svd_jacobi_tpu.analysis.recompile_guard import RecompileGuard
    from svd_jacobi_tpu.resilience import chaos
    if args.corrupt:
        chaos.corrupt_compile_cache(args.corrupt_dir or args.cache,
                                    mode=args.corrupt_mode)
    caught = []
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        with RecompileGuard() as guard:
            svc = _service(args, journal_path=None)
            svc.start()
            svc.warmup(timeout=300.0)
            rng = np.random.default_rng(0)
            a = rng.standard_normal((40, 30)).astype(np.float32)
            res = svc.submit(a).result(timeout=120.0)
            svc.stop()
        caught = [str(x.message) for x in w]
    print(json.dumps({
        "status": res.status.name,
        "warnings": caught,
        "backend_compiles": guard.backend_compiles,
        "cache_hits": guard.cache_hits,
        "fresh_backend_compiles": guard.fresh_backend_compiles(),
    }), flush=True)
    return 0


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("mode", choices=["serve", "resume", "cachecheck"])
    p.add_argument("--journal", default=None)
    p.add_argument("--cache", default=None)
    p.add_argument("--requests", type=int, default=4)
    p.add_argument("--kill-after", type=int, default=2)
    p.add_argument("--deadline-s", type=float, default=300.0)
    p.add_argument("--slow-s", type=float, default=0.05)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--warmup", action="store_true")
    p.add_argument("--corrupt", action="store_true")
    p.add_argument("--corrupt-mode", default="flip")
    p.add_argument("--corrupt-dir", default=None,
                   help="dir to corrupt (default: --cache root)")
    args = p.parse_args()
    if args.mode == "serve":
        return cmd_serve(args)
    if args.mode == "resume":
        return cmd_resume(args)
    return cmd_cachecheck(args)


if __name__ == "__main__":
    sys.exit(main())
