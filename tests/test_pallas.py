"""Property tests for the Pallas rotation kernels (ops/pallas_blocks.py).

Run under the Pallas interpreter on the CPU test backend (tests/conftest.py);
the compiled TPU kernel is bit-identical to `reference_cross`/`reference_self`
by construction (same body) and is exercised on hardware by bench.py and the
driver's entry check.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import svd_jacobi_tpu as sj
from svd_jacobi_tpu.config import SVDConfig
from svd_jacobi_tpu.ops import pallas_blocks as pb, rounds

HI = jax.lax.Precision.HIGHEST


def _gram(x):
    return jnp.einsum("kmi,kmj->kij", x, x, precision=HI,
                      preferred_element_type=jnp.float32)


def _rand_panels(k, m, n2, seed=0):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.standard_normal((k, m, n2)), jnp.float32)


@pytest.mark.parametrize("n2", [8, 32, 64])
def test_cross_q_orthogonal(n2):
    x = _rand_panels(3, 256, n2)
    q = pb.cross_rotations(_gram(x), interpret=True)
    qtq = jnp.einsum("kij,kil->kjl", q, q, precision=HI)
    err = jnp.max(jnp.abs(qtq - jnp.eye(n2)[None]))
    assert float(err) < 5e-6


@pytest.mark.parametrize("n2", [8, 32, 64])
def test_self_q_orthogonal(n2):
    x = _rand_panels(3, 256, n2)
    q = pb.self_rotations(_gram(x), interpret=True)
    qtq = jnp.einsum("kij,kil->kjl", q, q, precision=HI)
    err = jnp.max(jnp.abs(qtq - jnp.eye(n2)[None]))
    assert float(err) < 5e-6


def test_pallas_matches_reference_body():
    """interpret=True pallas_call vs the pure-jnp reference: equivalent to
    the f32 floor (op scheduling may differ slightly between the two
    compilations, so bit-identity is not guaranteed on every backend)."""
    g = _gram(_rand_panels(2, 128, 32))
    assert float(jnp.max(jnp.abs(
        pb.cross_rotations(g, interpret=True) - pb.reference_cross(g)))) < 1e-5
    assert float(jnp.max(jnp.abs(
        pb.self_rotations(g, interpret=True) - pb.reference_self(g)))) < 1e-5


def test_diagonal_gram_gives_identity():
    """Already-orthogonal columns: every rotation is skipped and the
    tournament bookkeeping must restore the original slot order exactly —
    Q == I bit-for-bit (this pins the roll/circle-move bookkeeping)."""
    k, n2 = 3, 32
    rng = np.random.default_rng(1)
    d = jnp.asarray(rng.uniform(0.5, 2.0, (k, n2)), jnp.float32)
    g = jnp.einsum("ki,ij->kij", d, jnp.eye(n2, dtype=jnp.float32))
    eye = jnp.broadcast_to(jnp.eye(n2, dtype=jnp.float32)[None], (k, n2, n2))
    assert float(jnp.max(jnp.abs(pb.cross_rotations(g, interpret=True) - eye))) == 0.0
    assert float(jnp.max(jnp.abs(pb.self_rotations(g, interpret=True) - eye))) == 0.0


def test_cross_contracts_coupling():
    """One cross call reduces the cross-block coupling mass of each panel."""
    x = _rand_panels(3, 512, 64)
    g = _gram(x)
    b = 32
    q = pb.cross_rotations(g, interpret=True)
    xn = jnp.einsum("kmi,kij->kmj", x, q, precision=HI)
    gn = _gram(xn)
    before = float(jnp.linalg.norm(g[:, :b, b:]))
    after = float(jnp.linalg.norm(gn[:, :b, b:]))
    assert after < 0.8 * before


def test_self_sweeps_converge_as_eigensolver():
    """Iterated self rounds diagonalize the panel Gram (block Jacobi on a
    single block is a full Jacobi eigensolver)."""
    x = _rand_panels(2, 256, 32, seed=3)
    for _ in range(8):
        q = pb.self_rotations(_gram(x), interpret=True)
        x = jnp.einsum("kmi,kij->kmj", x, q, precision=HI)
    g = _gram(x)
    off = jnp.max(jnp.abs(g * (1 - jnp.eye(32)[None])))
    scale = jnp.max(jnp.abs(g))
    assert float(off / scale) < 1e-5


def test_panel_stats_masked_vs_unmasked():
    """A numerically-null column is deflated from the masked stat but not
    the skip stat; exactly-zero columns contribute to neither."""
    m, n2 = 128, 8
    rng = np.random.default_rng(4)
    x = np.asarray(rng.standard_normal((1, m, n2)), np.float32)
    x[:, :, 5] = x[:, :, 0] * 1e-8          # null-norm column, coupled to col 0
    x[:, :, 7] = 0.0                        # exactly-zero (padding) column
    g = _gram(jnp.asarray(x))
    dmax2 = jnp.max(jnp.diagonal(g[0]))
    masked, unmasked = rounds.panel_stats(g, dmax2)
    assert float(unmasked) > 0.9            # sees the parallel null column
    # The masked stat deflates that ~1.0 pair; what remains is the ordinary
    # O(1/sqrt(m)) mutual coherence of the random live columns.
    assert float(masked) < 0.5
    # zero column contributed nothing (no NaN/Inf)
    assert np.isfinite(float(masked)) and np.isfinite(float(unmasked))


@pytest.mark.parametrize("precondition", ["on", "off"])
def test_solver_pallas_path(precondition):
    rng = np.random.default_rng(5)
    n = 96
    a = jnp.asarray(rng.standard_normal((n, n)), jnp.float32)
    r = sj.svd(a, config=SVDConfig(pair_solver="pallas",
                                   precondition=precondition))
    an = np.asarray(a, np.float64)
    s_ref = np.linalg.svd(an, compute_uv=False)
    un = np.asarray(r.u, np.float64)
    vn = np.asarray(r.v, np.float64)
    sn = np.asarray(r.s, np.float64)
    assert np.max(np.abs(sn - s_ref)) / s_ref[0] < 5e-6
    assert np.max(np.abs(un.T @ un - np.eye(n))) < 2e-5
    assert np.max(np.abs(vn.T @ vn - np.eye(n))) < 2e-5
    res = np.linalg.norm(un @ np.diag(sn) @ vn.T - an) / np.linalg.norm(an)
    assert res < 1e-5


def test_solver_pallas_odd_block():
    """n that forces an odd ceil(n/nblocks): the even-b fixup must hold
    (regression: 65x65 crashed the kernel shape check)."""
    rng = np.random.default_rng(6)
    a = jnp.asarray(rng.standard_normal((65, 65)), jnp.float32)
    r = sj.svd(a, config=SVDConfig(pair_solver="pallas"))
    s_ref = np.linalg.svd(np.asarray(a, np.float64), compute_uv=False)
    assert np.max(np.abs(np.asarray(r.s, np.float64) - s_ref)) / s_ref[0] < 5e-6


def test_solver_pallas_bf16():
    rng = np.random.default_rng(7)
    a = jnp.asarray(rng.standard_normal((80, 64)), jnp.bfloat16)
    r = sj.svd(a, config=SVDConfig(pair_solver="pallas"))
    s_ref = np.linalg.svd(np.asarray(a, np.float64), compute_uv=False)
    assert r.s.dtype == jnp.bfloat16
    assert np.max(np.abs(np.asarray(r.s, np.float64) - s_ref)) / s_ref[0] < 0.02


def test_solver_pallas_novec():
    rng = np.random.default_rng(8)
    a = jnp.asarray(rng.standard_normal((96, 80)), jnp.float32)
    r = sj.svd(a, compute_u=False, compute_v=False,
               config=SVDConfig(pair_solver="pallas"))
    assert r.u is None and r.v is None
    s_ref = np.linalg.svd(np.asarray(a, np.float64), compute_uv=False)
    assert np.max(np.abs(np.asarray(r.s, np.float64) - s_ref)) / s_ref[0] < 5e-6


def test_solver_pallas_f64_rejected():
    a = jnp.zeros((80, 80), jnp.float32).astype(jnp.float64) \
        if jax.config.jax_enable_x64 else None
    if a is None:
        pytest.skip("x64 disabled")
    with pytest.raises(ValueError, match="float32"):
        sj.svd(a, config=SVDConfig(pair_solver="pallas"))


def test_solver_pallas_matches_qr_svd():
    """The kernel path and the XLA qr-svd path agree on sigma."""
    rng = np.random.default_rng(9)
    a = jnp.asarray(rng.standard_normal((96, 96)), jnp.float32)
    r1 = sj.svd(a, config=SVDConfig(pair_solver="pallas"))
    r2 = sj.svd(a, config=SVDConfig(pair_solver="qr-svd"))
    smax = float(r2.s[0])
    assert np.max(np.abs(np.asarray(r1.s, np.float64)
                         - np.asarray(r2.s, np.float64))) / smax < 5e-6


def test_pick_block_k_odd_counts():
    """Odd panel counts above the VMEM budget must still be reduced: the
    chunk size is the largest DIVISOR within budget, not a power-of-2
    halving (regression: k=17 at b=128 blew the 16 MB scoped-VMEM limit)."""
    for k in (17, 34, 51, 9, 15):
        bk = pb._pick_block_k(k, 128, factor=3)
        assert k % bk == 0
        assert bk * 8 * 128 * 128 * 4 * 3 <= (14 << 20)
    # within-budget counts stay whole
    assert pb._pick_block_k(8, 128, factor=3) == 8


def test_sharded_novec_pallas():
    """Sigma-only sharded solve on the kernel path (regression: zero-width
    V placeholders tripped cond variance checking)."""
    from svd_jacobi_tpu.parallel import sharded, launch

    mesh = sharded.make_mesh()
    a = launch.sharded_input(96, 96, mesh)
    r = sharded.svd(a, mesh=mesh, compute_u=False, compute_v=False)
    assert r.u is None and r.v is None
    s_ref = np.linalg.svd(np.asarray(a, np.float64), compute_uv=False)
    assert np.max(np.abs(np.asarray(r.s, np.float64) - s_ref)) / s_ref[0] < 5e-6


# --- fused apply+exchange kernel (ops/pallas_apply.py) ---

from svd_jacobi_tpu.ops import pallas_apply as pa
from svd_jacobi_tpu.parallel import schedule as sched


@pytest.mark.parametrize("k,m,exchange", [
    (4, 256, True), (4, 256, False), (1, 256, True), (8, 1000, True)])
def test_apply_exchange_matches_reference_chain(k, m, exchange):
    """The fused kernel must equal the concat @ q + slice (+ rotate_blocks)
    chain it replaces, to f32 dot-reassociation rounding."""
    rng = np.random.default_rng(0)
    b = 128
    top = jnp.asarray(rng.standard_normal((k, m, b)), jnp.float32)
    bot = jnp.asarray(rng.standard_normal((k, m, b)), jnp.float32)
    q = jnp.asarray(rng.standard_normal((k, 2 * b, 2 * b)), jnp.float32)
    nt, nb = pa.apply_exchange(top, bot, q, exchange=exchange, interpret=True)
    xn = jnp.einsum("kmi,kij->kmj", jnp.concatenate([top, bot], -1), q,
                    precision=HI)
    rt, rb = xn[..., :b], xn[..., b:]
    if exchange:
        rt, rb = sched.rotate_blocks(rt, rb)
    scale = float(jnp.max(jnp.abs(xn)))
    assert float(jnp.max(jnp.abs(nt - rt))) < 2e-5 * scale
    assert float(jnp.max(jnp.abs(nb - rb))) < 2e-5 * scale


def test_apply_exchange_bf16_stored_qx2_angles():
    """bf16-STORED stacks under x3 (the mixed_store="bf16"/"bf16g" bulk):
    the kernel must split the f32 q into two bf16 passes (qx2) instead of
    casting it — a bf16-cast q floors rotation angles at eps_bf16 and
    stalls the bulk at ~5e-3 coupling (measured on-chip). Verify the qx2
    result tracks the exact product on the SAME bf16-valued stacks to
    ~eps_bf16^2, an order below the bf16-cast-q error."""
    rng = np.random.default_rng(3)
    k, m, b = 2, 256, 128
    top = jnp.asarray(rng.standard_normal((k, m, b)), jnp.bfloat16)
    bot = jnp.asarray(rng.standard_normal((k, m, b)), jnp.bfloat16)
    q = jnp.asarray(np.stack([np.linalg.qr(
        rng.standard_normal((2 * b, 2 * b)))[0] for _ in range(k)]),
        jnp.float32)
    nt, nb = pa.apply_exchange(top, bot, q, x3=True, interpret=True)
    # Exact product on the bf16-valued stacks (storage rounding excluded —
    # it is the q-side error being bounded here).
    xf = jnp.concatenate([top, bot], -1).astype(jnp.float32)
    xn = jnp.einsum("kmi,kij->kmj", xf, q, precision=HI)
    rt, rb = sched.rotate_blocks(xn[..., :b], xn[..., b:])
    scale = float(jnp.max(jnp.abs(xn)))
    err = max(float(jnp.max(jnp.abs(nt.astype(jnp.float32) - rt))),
              float(jnp.max(jnp.abs(nb.astype(jnp.float32) - rb))))
    # bf16 OUTPUT storage rounding alone is ~4e-3*scale; a bf16-cast q
    # would add ~4e-3*sqrt(2b)*scale on top. qx2 must stay at the
    # storage-rounding level.
    assert err < 5e-3 * scale, err
    # And the same contract through rounds._einsum (the non-fused path).
    e2 = rounds._einsum(jnp.concatenate([top, bot], -1), q, "kmi,kij->kmj",
                        x3=True)
    err2 = float(jnp.max(jnp.abs(e2 - xn))) / scale
    assert err2 < 5e-4, err2


def test_apply_exchange_perm_maps_match_rotate_blocks():
    """The kernel's closed-form output-slot maps must encode exactly one
    schedule.rotate_blocks step, for every stack width."""
    for k in (1, 2, 3, 5, 8):
        pair_t, half_t, pair_b, half_b = pa._perm_maps(k, exchange=True)
        top = np.arange(k)          # slot id of each pair's top result
        bot = np.arange(k, 2 * k)   # ... and bottom result
        want_t, want_b = sched.rotate_indices(top, bot)
        got_t = np.where(half_t, top[pair_t], bot[pair_t])
        got_b = np.where(half_b, top[pair_b], bot[pair_b])
        assert np.array_equal(got_t, want_t), k
        assert np.array_equal(got_b, want_b), k


def test_oversized_rotation_panels_fall_back_to_reference():
    """Panels beyond the rotation kernels' scoped-VMEM budget (explicit
    block_size >= 512: per-panel live set ~8 MB x double-buffering) must
    route to the XLA reference bodies instead of dying in Mosaic
    (PROFILE.md item 18). This test passes ONLY via the fallback: it calls
    the dispatcher with interpret=False on the CPU backend, where the
    compiled-kernel branch could not run at all."""
    assert not pb.kernel_fits(512, pb.CROSS_FACTOR)
    assert pb.kernel_fits(256, pb.CROSS_FACTOR)
    assert pb.kernel_fits(128, pb.SELF_FACTOR)
    x = _rand_panels(1, 64, 1024, seed=9)   # b2 = 512 cross panel
    q = rounds._rotations(_gram(x), "cross", interpret=False, polish=False,
                          axis_name=None)
    qtq = jnp.einsum("kij,kil->kjl", q, q, precision=HI)
    assert float(jnp.max(jnp.abs(qtq - jnp.eye(1024)[None]))) < 1e-4


def test_apply_exchange_support_predicate():
    assert pa.supported(2048, 128)
    assert pa.supported(5000, 128)      # chunk 1000 divides
    assert not pa.supported(97, 128)    # no usable row chunk
    assert not pa.supported(2048, 64)   # sub-lane panel width
    # wide user panels must respect the per-step VMEM budget: the chunk
    # limit shrinks with b, and b >= 1024 is rejected outright
    assert pa._chunk_limit(512) < pa._chunk_limit(128)
    assert not pa.supported(8192, 1024)
    per_step = (6 * pa._chunk_limit(512) * 512 + 2 * 2 * 512 * 512) * 4
    assert per_step <= (13 << 20) // 2


# --- Gram panel kernel (ops/pallas_gram.py) ---

from svd_jacobi_tpu.ops import pallas_gram as pg


@pytest.mark.parametrize("k,m", [(4, 512), (8, 1000), (1, 256)])
def test_gram_pairs_matches_einsum(k, m):
    """The accumulating reduction kernel must equal the concat + einsum
    Gram panel (to f32 reduction-order rounding; interpret mode is
    bit-exact since both reduce in the same chunk order)."""
    rng = np.random.default_rng(1)
    b = 128
    top = jnp.asarray(rng.standard_normal((k, m, b)), jnp.float32)
    bot = jnp.asarray(rng.standard_normal((k, m, b)), jnp.float32)
    g = pg.gram_pairs(top, bot, interpret=True)
    x = jnp.concatenate([top, bot], -1)
    ref = jnp.einsum("kmi,kmj->kij", x, x, precision=HI)
    scale = float(jnp.max(jnp.abs(ref)))
    assert float(jnp.max(jnp.abs(g - ref))) < 2e-5 * scale
    # symmetry comes from construction (gxy mirrored into both triangles)
    assert float(jnp.max(jnp.abs(g - g.transpose(0, 2, 1)))) == 0.0


def test_gram_pairs_support_predicate():
    assert pg.supported(2048, 128)
    assert not pg.supported(97, 128)
    assert not pg.supported(2048, 64)
    # the gram step's smaller footprint (2 input blocks + 3 accumulators)
    # keeps wide panels inside the VMEM budget where the apply kernel's
    # 6-block footprint already shrinks its chunk
    from svd_jacobi_tpu.ops import pallas_apply as pa
    assert pg._chunk(8192, 512) >= pa._pick_chunk(8192, 512)
    per_step = (2 * pg._chunk(8192, 512) * 512 + 3 * 512 * 512) * 4
    assert per_step <= (13 << 20) // 2


@pytest.mark.parametrize("gram_bf16", [False, True])
def test_apply_exchange_with_gram_matches_standalone(gram_bf16):
    """The fused gram epilogue (with_gram=True) must equal the standalone
    gram kernel / einsum on the post-exchange pairs — the next round's
    panels for free."""
    from svd_jacobi_tpu.ops import pallas_gram as pg
    rng = np.random.default_rng(3)
    k, m, b = 4, 256, 128
    top = jnp.asarray(rng.standard_normal((k, m, b)), jnp.float32)
    bot = jnp.asarray(rng.standard_normal((k, m, b)), jnp.float32)
    q = jnp.asarray(rng.standard_normal((k, 2 * b, 2 * b)), jnp.float32)
    nt, nb, g = pa.apply_exchange(top, bot, q, interpret=True,
                                  with_gram=True, gram_bf16=gram_bf16)
    nt2, nb2 = pa.apply_exchange(top, bot, q, interpret=True)
    np.testing.assert_array_equal(np.asarray(nt), np.asarray(nt2))
    np.testing.assert_array_equal(np.asarray(nb), np.asarray(nb2))
    x = jnp.concatenate([nt, nb], axis=-1)
    if gram_bf16:
        ref = jnp.einsum("kmi,kmj->kij", x.astype(jnp.bfloat16),
                         x.astype(jnp.bfloat16),
                         preferred_element_type=jnp.float32)
        tol = 5e-2   # single-pass bf16 rounding differences
    else:
        ref = jnp.einsum("kmi,kmj->kij", x, x, precision=HI)
        tol = 1e-4
    scale = float(jnp.max(jnp.abs(ref)))
    assert float(jnp.max(jnp.abs(g - ref))) < tol * scale
    # symmetric by construction
    np.testing.assert_allclose(np.asarray(g),
                               np.asarray(g.transpose(0, 2, 1)), rtol=0,
                               atol=1e-6 * scale)
    with pytest.raises(ValueError, match="exchange"):
        pa.apply_exchange(top, bot, q, exchange=False, with_gram=True)


def test_gram_carried_fused_loop_matches_unfused_sweep():
    """The compiled path's gram-carried loop (bootstrap panel +
    cross_round_fused scan) must converge identically to the unfused
    reference sweep: same pair coverage, agreeing couplings and stacks to
    rotation-angle rounding."""
    from svd_jacobi_tpu.ops import pallas_gram as pg
    from svd_jacobi_tpu.ops import rounds
    rng = np.random.default_rng(4)
    k, m, b = 2, 256, 128
    top = jnp.asarray(rng.standard_normal((k, m, b)), jnp.float32)
    bot = jnp.asarray(rng.standard_normal((k, m, b)), jnp.float32)
    dmax2 = rounds._global_dmax2(top, bot)

    # Unfused reference semantics (the interpret path sweep).
    rt, rb, _, _, off_ref = rounds.sweep(
        top, bot, None, None, dmax2, 0.0, interpret=True, polish=True,
        bf16_gram=False)

    # Gram-carried fused structure, interpret kernels.
    blocks = jnp.concatenate([top, bot], axis=0)
    blocks, _, rel_self = rounds.self_round(
        blocks, None, dmax2, 0.0, interpret=True, polish=True,
        bf16_gram=False)
    ft, fb = blocks[:k], blocks[k:]
    g = pg.gram_pairs(ft, fb, interpret=True)
    off = rel_self
    for _ in range(rounds.sched.num_rounds(2 * k)):
        ft, fb, _, _, g, stat = rounds.cross_round_fused(
            ft, fb, None, None, g, dmax2, 0.0, polish=True,
            bf16_gram=False, interpret=True)
        off = jnp.maximum(off, stat)
    # The fused panels differ from the stored-value grams by reduction-
    # order rounding, and Jacobi ANGLES amplify that chaotically across
    # rounds — the loops are equivalent algorithms, not bitwise twins. The
    # invariants that must agree: the convergence statistic, and (for
    # each loop) exact preservation of the input's singular values — one
    # sweep is an orthogonal right-transform, fused or not.
    assert abs(float(off) - float(off_ref)) < 5e-3

    def glob(t, b_):
        return np.asarray(jnp.concatenate(
            [jnp.concatenate([t, b_], axis=0)[i] for i in range(2 * k)],
            axis=1), np.float64)

    s_in = np.linalg.svd(glob(top, bot), compute_uv=False)
    for t, b_ in ((ft, fb), (rt, rb)):
        s_out = np.linalg.svd(glob(t, b_), compute_uv=False)
        np.testing.assert_allclose(s_out, s_in, rtol=0,
                                   atol=1e-4 * s_in[0])
