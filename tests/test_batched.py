"""Batched solve lane (`solver.svd_batched` / `BatchedSweepStepper`) and
the serving layer's request coalescing (`SVDService` with max_batch > 1).

The claims under test, per member of a batch:

  * ORACLE EQUALITY — a batched solve's factors/sigmas/residuals match the
    sequential path to tolerance, across both lanes (Pallas stacked f32,
    vmapped XLA f64) and with zero-padded tail slots;
  * STATUS ISOLATION — one chaos-NaN member reports NONFINITE while its
    neighbors stay OK with in-tolerance residuals (statistics are
    per-member segments, blocks never meet across members);
  * DEADLINE DECODE — a coalesced dispatch's effective deadline is the
    min over members; members at tolerance decode OK (tolerance wins),
    the rest DEADLINE;
  * ADMISSION — a queued request's deadline promise is released the
    moment it is cancelled (the PR-5 satellite bugfix), and the batched
    retrace contract catches a tier leak (failing fixture).

All CPU, all in tier-1.
"""

import threading
import time

import numpy as np
import jax.numpy as jnp
import pytest

from svd_jacobi_tpu import SVDConfig, svd, svd_batched
from svd_jacobi_tpu.resilience import chaos
from svd_jacobi_tpu.serve import (AdmissionError, AdmissionQueue,
                                  AdmissionReason, Bucket, ServeConfig,
                                  SVDService, Ticket)
from svd_jacobi_tpu.serve.queue import Request
from svd_jacobi_tpu.solver import (BatchedSweepStepper, SolveStatus,
                                   SweepStepper)
from svd_jacobi_tpu.utils import matgen, validation


def _stack(shapes_seed, m, n, dtype, count):
    mats = [matgen.random_dense(m, n, seed=shapes_seed + i, dtype=dtype)
            for i in range(count)]
    return mats, jnp.stack(mats)


def _residual(a, u, s, v):
    return float(np.asarray(validation.relative_residual(a, u, s, v)))


class TestSvdBatchedOracle:
    def test_pallas_lane_matches_sequential(self):
        mats, a = _stack(100, 80, 64, jnp.float32, 3)
        r = svd_batched(a)
        assert [SolveStatus(int(c)) for c in np.asarray(r.status)] == \
            [SolveStatus.OK] * 3
        for i, m_i in enumerate(mats):
            ri = svd(m_i)
            np.testing.assert_allclose(np.asarray(r.s[i]),
                                       np.asarray(ri.s), rtol=1e-5)
            assert _residual(m_i, r.u[i], r.s[i], r.v[i]) < 1e-5

    def test_xla_lane_matches_sequential_f64(self):
        cfg = SVDConfig(block_size=4)
        mats, a = _stack(200, 32, 24, jnp.float64, 4)
        r = svd_batched(a, config=cfg)
        assert [SolveStatus(int(c)) for c in np.asarray(r.status)] == \
            [SolveStatus.OK] * 4
        for i, m_i in enumerate(mats):
            ri = svd(m_i, config=cfg)
            np.testing.assert_allclose(np.asarray(r.s[i]),
                                       np.asarray(ri.s), rtol=1e-12)
            assert _residual(m_i, r.u[i], r.s[i], r.v[i]) < 1e-13

    def test_zero_tail_slots_are_exact(self):
        # The service's tier padding: all-zero members ride along without
        # perturbing real members, and report OK themselves.
        mats, _ = _stack(300, 64, 64, jnp.float32, 2)
        a = jnp.stack(mats + [jnp.zeros((64, 64), jnp.float32)] * 2)
        r = svd_batched(a)
        assert [SolveStatus(int(c)) for c in np.asarray(r.status)] == \
            [SolveStatus.OK] * 4
        for i, m_i in enumerate(mats):
            ri = svd(m_i)
            np.testing.assert_allclose(np.asarray(r.s[i]),
                                       np.asarray(ri.s), rtol=1e-5)
            assert _residual(m_i, r.u[i], r.s[i], r.v[i]) < 1e-5
        assert float(jnp.max(jnp.abs(r.s[2:]))) == 0.0

    def test_wide_stack_transposes(self):
        mats, a = _stack(400, 24, 32, jnp.float64, 2)
        cfg = SVDConfig(block_size=4)
        r = svd_batched(a, config=cfg)
        for i, m_i in enumerate(mats):
            assert r.u[i].shape == (24, 24) and r.v[i].shape == (32, 24)
            assert _residual(m_i, r.u[i], r.s[i], r.v[i]) < 1e-13

    def test_batched_rejects_fused_only_modes(self):
        _, a = _stack(500, 64, 64, jnp.float32, 2)
        with pytest.raises(ValueError, match="mixed_bulk"):
            svd_batched(a, config=SVDConfig(mixed_bulk=True))
        with pytest.raises(ValueError, match="donate_input"):
            svd_batched(a, config=SVDConfig(donate_input=True))
        with pytest.raises(ValueError, match="double"):
            svd_batched(a, config=SVDConfig(precondition="double"))


class TestMixedStatusBatch:
    def test_chaos_nan_member_isolated(self):
        """One chaos-NaN member -> NONFINITE; neighbors OK with
        in-tolerance residuals (the per-member health-word claim)."""
        mats, a = _stack(600, 64, 64, jnp.float32, 3)
        with chaos.nan_at_sweep(1):
            r = svd_batched(a)
        names = [SolveStatus(int(c)).name for c in np.asarray(r.status)]
        assert names[0] == "NONFINITE", names
        assert names[1:] == ["OK", "OK"], names
        for i in (1, 2):
            assert _residual(mats[i], r.u[i], r.s[i], r.v[i]) < 1e-5

    def test_stepper_nan_member_isolated(self):
        mats, a = _stack(700, 64, 64, jnp.float32, 3)
        st = BatchedSweepStepper(a, config=SVDConfig())
        state = st.init()
        steps = 0
        while st.should_continue(state):
            if steps == 1:
                state = state._replace(
                    top=state.top.at[0, 0, 0].set(jnp.nan))
            state = st.step(state)
            steps += 1
        r = st.finish(state)
        names = [SolveStatus(int(c)).name for c in np.asarray(r.status)]
        assert names[0] == "NONFINITE" and names[1:] == ["OK", "OK"]
        for i in (1, 2):
            assert _residual(mats[i], r.u[i], r.s[i], r.v[i]) < 1e-5


class TestBatchedDeadlineDecode:
    def test_min_deadline_stops_batch_tolerance_wins(self):
        """An already-expired batch deadline stops the stack before the
        first sweep: every member decodes DEADLINE (none is at
        tolerance)."""
        _, a = _stack(800, 32, 32, jnp.float64, 3)
        st = BatchedSweepStepper(a, config=SVDConfig(block_size=4))
        st.set_control(deadline=time.monotonic() - 1.0)
        state = st.init()
        assert not st.should_continue(state)
        r = st.finish(state)
        assert [SolveStatus(int(c)) for c in np.asarray(r.status)] == \
            [SolveStatus.DEADLINE] * 3
        assert list(np.asarray(r.sweeps)) == [0, 0, 0]

    def test_converged_members_decode_ok_at_deadline(self):
        """Deadline fires AFTER convergence: tolerance wins — OK, not
        DEADLINE (matching the single stepper's decode order)."""
        _, a = _stack(900, 32, 32, jnp.float64, 2)
        st = BatchedSweepStepper(a, config=SVDConfig(block_size=4))
        state = st.init()
        while st.should_continue(state):
            state = st.step(state)
        r = st.finish(state)
        assert [SolveStatus(int(c)) for c in np.asarray(r.status)] == \
            [SolveStatus.OK] * 2
        # Now install an expired control and re-decode: converged members
        # must still read OK.
        st.set_control(deadline=time.monotonic() - 1.0)
        st.should_continue(state)
        r2 = st.finish(state)
        assert [SolveStatus(int(c)) for c in np.asarray(r2.status)] == \
            [SolveStatus.OK] * 2

    def test_all_members_cancelled_stops_batch(self):
        _, a = _stack(1000, 32, 32, jnp.float64, 2)
        st = BatchedSweepStepper(a, config=SVDConfig(block_size=4))
        st.set_control(should_cancel=lambda: True)
        state = st.init()
        assert not st.should_continue(state)
        r = st.finish(state)
        assert [SolveStatus(int(c)) for c in np.asarray(r.status)] == \
            [SolveStatus.CANCELLED] * 2


BUCKETS64 = ((32, 32, "float64"),)
SOLVER64 = SVDConfig(block_size=4)


def _coalescing_cfg(**over):
    base = dict(buckets=BUCKETS64, solver=SOLVER64, max_queue_depth=16,
                max_batch=4, batch_window_s=0.25, batch_tiers=(1, 4))
    base.update(over)
    return ServeConfig(**base)


@pytest.mark.serve
class TestServiceCoalescing:
    def test_padded_tier_dispatch_matches_oracle(self):
        """3 same-bucket requests coalesce into ONE tier-4 dispatch
        (padded tail slot); per-member factors match the numpy oracle and
        the serve records carry the shared batch identity."""
        mats = [matgen.random_dense(32, 24, seed=40 + i, dtype=jnp.float64)
                for i in range(3)]
        with SVDService(_coalescing_cfg()) as svc:
            tickets = [svc.submit(a) for a in mats]
            results = [t.result(timeout=180.0) for t in tickets]
            recs = svc.records()
        for a, res in zip(mats, results):
            assert res.status is SolveStatus.OK
            sref = np.linalg.svd(np.asarray(a), compute_uv=False)
            np.testing.assert_allclose(np.asarray(res.s), sref, atol=1e-12)
            assert _residual(a, res.u, res.s, res.v) < 1e-13
        batch_ids = {r.get("batch_id") for r in recs}
        assert len(batch_ids) == 1 and None not in batch_ids
        assert all(r.get("batch_size") == 3 and r.get("batch_tier") == 4
                   for r in recs)

    def test_numpy_submission_stays_host_until_dispatch(self):
        """numpy input is admitted without a device put and solves to the
        same answer (the host-admission fast path)."""
        a = np.asarray(matgen.random_dense(30, 20, seed=77,
                                           dtype=jnp.float64))
        with SVDService(_coalescing_cfg()) as svc:
            res = svc.submit(a).result(timeout=180.0)
        assert res.status is SolveStatus.OK
        sref = np.linalg.svd(a, compute_uv=False)
        np.testing.assert_allclose(np.asarray(res.s), sref, atol=1e-12)

    def test_nonfinite_numpy_rejected_at_door(self):
        a = np.full((8, 8), np.nan)
        with SVDService(_coalescing_cfg()) as svc:
            with pytest.raises(AdmissionError) as ei:
                svc.submit(a)
            assert ei.value.reason is AdmissionReason.NONFINITE_INPUT

    def test_mid_batch_cancel_and_deadline_decode(self):
        """Coalesced-dispatch control decode: the min-over-members
        deadline stops the batch (expired member -> DEADLINE with PARTIAL
        factors, like the serial lane's mid-solve stops); a member
        cancelled mid-solve decodes CANCELLED at finalize unless it
        reached tolerance first."""
        mats = [matgen.random_dense(32, 32, seed=60 + i,
                                    dtype=jnp.float64) for i in range(2)]
        svc = SVDService(_coalescing_cfg()).start()
        try:
            svc.warmup(timeout=300.0)   # no compile may eat the deadline
            with chaos.slow_solve(0.15, shots=1):
                t1 = svc.submit(mats[0], deadline_s=0.5)
                t2 = svc.submit(mats[1], deadline_s=60.0)
                time.sleep(0.2)
                t2.cancel()
                r1 = t1.result(timeout=120.0)
                r2 = t2.result(timeout=120.0)
        finally:
            svc.stop(drain=False, timeout=30.0)
        assert r1.status is SolveStatus.DEADLINE
        assert r1.s is not None, "DEADLINE member must get partial factors"
        assert r2.status is SolveStatus.CANCELLED
        recs = [r for r in svc.records()
                if not r["request"]["id"].startswith("warmup")]
        assert {r.get("batch_id") for r in recs} == {recs[0]["batch_id"]}

    def test_warmup_compiles_batched_tiers(self):
        from svd_jacobi_tpu import solver
        from svd_jacobi_tpu.analysis.recompile_guard import _cache_size
        # A bucket/tier no other test touches, so the pre-warm cache
        # cannot already hold it (the assertion is on NEW compiles).
        svc = SVDService(_coalescing_cfg(
            buckets=((34, 22, "float64"),), batch_tiers=(1, 3))).start()
        try:
            before = _cache_size(solver._sweep_step_xla_batched_jit)
            svc.warmup(timeout=300.0)
            after = _cache_size(solver._sweep_step_xla_batched_jit)
            assert after > before, "warmup must compile the batched tiers"
        finally:
            svc.stop(drain=False, timeout=30.0)


@pytest.mark.serve
class TestQueuedCancelReleasesBudget:
    """PR-5 satellite bugfix: a cancelled-while-queued request's deadline
    promise is released AT CANCEL, not held until pop."""

    def _req(self, rid, deadline_s):
        now = time.monotonic()
        t = Ticket(rid)
        return Request(
            id=rid, a=None, m=8, n=8, orig_shape=(8, 8), transposed=False,
            bucket=Bucket(8, 8, "float32"), compute_u=True, compute_v=True,
            degraded=False, deadline=now + deadline_s, deadline_s=deadline_s,
            submitted=now, cancel=t._cancel, ticket=t)

    def test_full_budget_queue_readmits_after_queued_cancel(self):
        q = AdmissionQueue(max_depth=8, max_deadline_budget_s=100.0)
        r1 = self._req("r1", 60.0)
        r2 = self._req("r2", 39.0)
        q.admit(r1)
        q.admit(r2)
        r3 = self._req("r3", 30.0)
        with pytest.raises(AdmissionError) as ei:
            q.admit(r3)
        assert ei.value.reason is AdmissionReason.DEADLINE_BUDGET
        # Cancel a QUEUED request: its promise must free immediately —
        # no pop, no worker involvement.
        r1.ticket.cancel()
        q.admit(r3)   # re-admission now succeeds
        assert q.depth() == 3

    def test_pop_same_bucket_leaves_other_buckets_queued(self):
        q = AdmissionQueue(max_depth=8)
        b1, b2 = Bucket(8, 8, "float32"), Bucket(16, 16, "float32")
        reqs = []
        for i, b in enumerate([b1, b2, b1, b2, b1]):
            r = self._req(f"r{i}", 60.0)
            r = Request(**{**r.__dict__, "bucket": b})
            q.admit(r)
            reqs.append(r)
        out = q.pop_same_bucket(b1, limit=8, deadline=None)
        assert [r.id for r in out] == ["r0", "r2", "r4"]
        assert q.depth() == 2
        assert q.pop(0.01).bucket == b2


@pytest.mark.serve
class TestBatchedRetraceFixture:
    """The batched compile-cache contract must demonstrably FAIL its
    fixture: two distinct tiers against an under-declared budget is
    exactly what a tier leak looks like."""

    ENTRIES = ("solver._sweep_step_xla_batched_jit",)

    def test_two_tiers_blow_underdeclared_budget(self):
        from svd_jacobi_tpu import solver
        from svd_jacobi_tpu.analysis.recompile_guard import RecompileGuard
        entries = {e: getattr(solver, e.split(".", 1)[1])
                   for e in self.ENTRIES}
        mats = [matgen.random_dense(24, 24, seed=50 + i, dtype=jnp.float64)
                for i in range(6)]
        cfg = SVDConfig(block_size=4)
        with RecompileGuard(budgets={e: 1 for e in self.ENTRIES},
                            entries=entries) as guard:
            for e in self.ENTRIES:
                guard.expect(e, problems=1)   # under-declared on purpose
            # Two DISTINCT batch tiers (2 and 4) through the batched
            # stepper — a second problem key the declaration denies.
            for count in (2, 4):
                st = BatchedSweepStepper(jnp.stack(mats[:count]),
                                         config=cfg)
                state = st.init()
                while st.should_continue(state):
                    state = st.step(state)
                st.finish(state)
            findings = guard.check()
        assert findings, "two tiers must blow an under-declared budget"
        assert all(f.code == "RETRACE001" for f in findings)


def test_build_serve_batch_fields_roundtrip():
    from svd_jacobi_tpu.obs import manifest
    rec = manifest.build_serve(
        request_id="r1", m=8, n=8, dtype="float32", bucket="8x8:float32",
        queue_wait_s=0.01, solve_time_s=0.02, status="OK", path="base",
        breaker="closed", brownout="FULL", batch_id="b00007",
        batch_size=3, batch_tier=4)
    manifest.validate(rec)
    assert (rec["batch_id"], rec["batch_size"], rec["batch_tier"]) == \
        ("b00007", 3, 4)
    assert "batch=b00007[3/4]" in manifest.summarize(rec)
    single = manifest.build_serve(
        request_id="r2", m=8, n=8, dtype="float32", bucket="8x8:float32",
        queue_wait_s=0.01, solve_time_s=0.02, status="OK", path="base",
        breaker="closed", brownout="FULL")
    assert single["batch_id"] is None and single["batch_tier"] is None
