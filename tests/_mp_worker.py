"""Worker process for tests/test_multiprocess.py — NOT a test module.

Runs one process of a 2-process CPU JAX cluster: bootstraps via
launch.initialize (the real jax.distributed.initialize branch, the one the
reference exercised by running on 2 MPI nodes, main.cu:1427-1442), builds
the global mesh, runs the sharded solve on a decomposition-invariant input,
and (on the coordinator) writes sigma to a file for the parent to check.
"""

import os
import sys


def main():
    coord, pid, nproc, outfile = sys.argv[1:5]
    mode = sys.argv[5] if len(sys.argv) > 5 else "solve"
    ckpt = sys.argv[6] if len(sys.argv) > 6 else None

    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=2")
    import jax
    jax.config.update("jax_platforms", "cpu")

    from svd_jacobi_tpu.parallel import launch, sharded

    ctx = launch.initialize(coordinator_address=coord,
                            num_processes=int(nproc),
                            process_id=int(pid))
    assert ctx.process_count == int(nproc), ctx
    assert ctx.global_device_count == 2 * int(nproc), ctx

    mesh = sharded.make_mesh()
    a = launch.sharded_input(96, 96, mesh, seed=11)

    if mode == "ckpt_save":
        # Phase 1 of the kill-and-resume test: run two sweeps, write the
        # per-process shard snapshots, and "crash" (exit without finish).
        from svd_jacobi_tpu.utils import checkpoint
        st = sharded.SweepStepper(a, mesh=mesh)
        # The multi-process snapshot flow must ride the kernel-path mesh
        # stepping (VERDICT r4 weak #3) — f32 input resolves to it.
        assert st._kernel_path, "mesh stepper downgraded off the kernel path"
        state = st.step(st.step(st.init()))
        checkpoint.save_state(ckpt, st, state)
        assert checkpoint._proc_path(ckpt).exists()
        print(f"worker {pid} saved", flush=True)
        return

    if mode == "ckpt_resume":
        # Phase 2: a fresh cluster resumes from the per-process files and
        # finishes through the one-call API.
        from svd_jacobi_tpu.utils import checkpoint
        r = checkpoint.svd_checkpointed(a, path=ckpt, mesh=mesh)
        assert not checkpoint._proc_path(ckpt).exists()  # removed on success
    else:
        r = sharded.svd(a, mesh=mesh)
    s = [float(x) for x in r.s]  # sigma is replicated -> addressable everywhere

    if ctx.is_coordinator:
        import json
        from svd_jacobi_tpu.solver import _host_scalar
        with open(outfile, "w") as f:
            json.dump({"s": s, "sweeps": int(_host_scalar(r.sweeps)),
                       "off": float(_host_scalar(r.off_rel)),
                       "process_count": ctx.process_count,
                       "global_devices": ctx.global_device_count}, f)
    print(f"worker {pid} done", flush=True)


if __name__ == "__main__":
    main()
